// Digest model metadata/config JSON into the harness's view of the model
// (reference model_parser.{h,cc}:39-142).

#pragma once

#include <map>
#include <memory>
#include <string>
#include <vector>

#include "client_backend.h"
#include "tjson.h"

namespace pa {

struct ModelTensor {
  std::string name;
  std::string datatype;
  std::vector<int64_t> shape;  // without batch dim
  bool is_shape_dynamic() const
  {
    for (int64_t d : shape) {
      if (d < 0) {
        return true;
      }
    }
    return false;
  }
};

enum class SchedulerType { NONE, DYNAMIC, SEQUENCE, ENSEMBLE };

class ModelParser {
 public:
  tc::Error Init(
      ClientBackend* backend, const std::string& model_name,
      const std::string& model_version);

  const std::string& ModelName() const { return model_name_; }
  const std::string& ModelVersion() const { return model_version_; }
  int MaxBatchSize() const { return max_batch_size_; }
  SchedulerType Scheduler() const { return scheduler_; }
  bool IsDecoupled() const { return decoupled_; }
  const std::vector<ModelTensor>& Inputs() const { return inputs_; }
  const std::vector<ModelTensor>& Outputs() const { return outputs_; }
  // ensemble composing-model names (empty for non-ensembles)
  const std::vector<std::string>& ComposingModels() const
  {
    return composing_models_;
  }

  // Fix dynamic input dims (reference --shape NAME:d1,d2,...); applied
  // on top of whatever Init parsed.  Unknown names error so typos are
  // caught before load generation.
  tc::Error OverrideShapes(
      const std::vector<std::pair<std::string, std::vector<int64_t>>>&
          overrides)
  {
    for (const auto& ov : overrides) {
      bool found = false;
      for (auto& input : inputs_) {
        if (input.name == ov.first) {
          input.shape = ov.second;
          found = true;
          break;
        }
      }
      if (!found) {
        return tc::Error(
            "--shape names unknown input '" + ov.first + "'");
      }
    }
    return tc::Error::Success;
  }

  // direct init for tests (no backend round-trip)
  void InitDirect(
      const std::string& name, int max_batch_size,
      std::vector<ModelTensor> inputs, std::vector<ModelTensor> outputs,
      SchedulerType scheduler = SchedulerType::NONE)
  {
    model_name_ = name;
    max_batch_size_ = max_batch_size;
    inputs_ = std::move(inputs);
    outputs_ = std::move(outputs);
    scheduler_ = scheduler;
  }

 private:
  std::string model_name_;
  std::string model_version_;
  int max_batch_size_ = 0;
  SchedulerType scheduler_ = SchedulerType::NONE;
  bool decoupled_ = false;
  std::vector<ModelTensor> inputs_;
  std::vector<ModelTensor> outputs_;
  std::vector<std::string> composing_models_;
};

}  // namespace pa
