// getopt_long option surface -> PerfAnalyzerParameters
// (reference command_line_parser.{h,cc}:706-759 — the load-shaping,
// measurement, model and transport options; CUDA-shm options map to
// XLA-shm).

#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "perf_utils.h"

namespace pa {

struct PerfAnalyzerParameters {
  std::string model_name;
  std::string model_version;
  std::string url = "localhost:8000";
  bool url_specified = false;  // -u given; else default follows protocol
  BackendKind kind = BackendKind::TRITON_HTTP;
  bool verbose = false;
  bool async = false;
  int batch_size = 1;
  bool zero_input = false;
  std::string input_data_path;  // JSON file of request payloads

  // concurrency sweep
  size_t concurrency_start = 1;
  size_t concurrency_end = 1;
  size_t concurrency_step = 1;
  // request-rate sweep (0 = concurrency mode)
  double request_rate_start = 0.0;
  double request_rate_end = 0.0;
  double request_rate_step = 1.0;
  Distribution request_distribution = Distribution::CONSTANT;
  std::string request_intervals_path;  // custom-interval mode

  uint64_t measurement_window_ms = 5000;
  bool count_windows = false;
  uint64_t measurement_request_count = 50;
  double stability_threshold_pct = 10.0;
  size_t max_trials = 10;

  bool use_sequences = false;
  size_t sequence_length = 20;
  double sequence_length_variation = 20.0;

  SharedMemoryType shared_memory = SharedMemoryType::NONE;
  size_t output_shm_size = 102400;

  std::string latency_report_file;  // CSV path
  uint32_t seed = 17;
  size_t num_threads = 2;  // rate-mode sender threads

  bool usage_requested = false;
};

class CLParser {
 public:
  // Parses argv; returns false (with *error set) on invalid input.
  static bool Parse(
      int argc, char** argv, PerfAnalyzerParameters* params,
      std::string* error);

  static std::string Usage();
};

}  // namespace pa
