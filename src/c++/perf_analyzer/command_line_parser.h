// getopt_long option surface -> PerfAnalyzerParameters
// (reference command_line_parser.{h,cc}:706-759 — the load-shaping,
// measurement, model and transport options; CUDA-shm options map to
// XLA-shm).

#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "perf_utils.h"

namespace pa {

struct PerfAnalyzerParameters {
  std::string model_name;
  std::string model_version;
  std::string url = "localhost:8000";
  bool url_specified = false;  // -u given; else default follows protocol
  BackendKind kind = BackendKind::TRITON_HTTP;
  // -i grpc was given (kind tracks the triton backend pair; non-triton
  // kinds consult this to pick their own wire, e.g. TF-Serving REST vs
  // gRPC PredictService)
  bool protocol_grpc = false;
  bool verbose = false;
  bool async = false;
  // in-process mode: path of the tpuserver python tree (role of
  // reference --triton-server-directory)
  std::string server_src;
  std::string server_zoo = "default";  // model set for in-process mode
  int batch_size = 1;
  bool zero_input = false;
  std::string input_data_path;  // JSON file of request payloads

  // concurrency sweep
  size_t concurrency_start = 1;
  size_t concurrency_end = 1;
  size_t concurrency_step = 1;
  // request-rate sweep (0 = concurrency mode)
  double request_rate_start = 0.0;
  double request_rate_end = 0.0;
  double request_rate_step = 1.0;
  Distribution request_distribution = Distribution::CONSTANT;
  std::string request_intervals_path;  // custom-interval mode

  uint64_t measurement_window_ms = 5000;
  bool count_windows = false;
  uint64_t measurement_request_count = 50;
  double stability_threshold_pct = 10.0;
  size_t max_trials = 10;

  // sweep termination + search mode (reference -l / --binary-search,
  // inference_profiler.h:243-297): 0 = no latency limit
  uint64_t latency_threshold_ms = 0;
  bool binary_search = false;
  // stability checks use p<N> latency instead of average when nonzero
  // (reference --percentile)
  size_t percentile = 0;
  // requests issued and discarded before the first window per level
  size_t warmup_request_count = 0;

  // gRPC bidi-stream issuance (reference --streaming)
  bool streaming = false;

  bool use_sequences = false;
  size_t sequence_length = 20;
  double sequence_length_variation = 20.0;
  uint64_t start_sequence_id = 1;
  uint64_t sequence_id_range = 0;  // 0 = unbounded

  // synthetic BYTES input shaping (reference --string-length/--string-data)
  size_t string_length = 128;
  std::string string_data;

  SharedMemoryType shared_memory = SharedMemoryType::NONE;
  size_t output_shm_size = 102400;

  // server-side trace forwarding (reference command_line_parser.cc:750-754)
  std::string trace_file;
  std::string trace_level;
  uint64_t trace_rate = 0;
  uint64_t trace_count = 0;
  uint64_t log_frequency = 0;

  // Prometheus metrics collection (reference --collect-metrics et al.)
  bool collect_metrics = false;
  std::string metrics_url;  // default: http://<url>/metrics
  uint64_t metrics_interval_ms = 1000;

  bool verbose_csv = false;

  // multi-process coordination (reference --enable-mpi, mpi_utils.h:32-83)
  bool enable_mpi = false;

  std::string latency_report_file;  // CSV path
  uint32_t seed = 17;
  size_t num_threads = 2;  // rate-mode sender threads

  // TLS (reference --ssl-grpc-* / --ssl-https-* families,
  // reference command_line_parser.cc:706-759)
  bool ssl_grpc_use_ssl = false;
  std::string ssl_grpc_root_certifications_file;
  std::string ssl_grpc_private_key_file;
  std::string ssl_grpc_certificate_chain_file;
  long ssl_https_verify_peer = 1;
  long ssl_https_verify_host = 2;
  std::string ssl_https_ca_certificates_file;
  std::string ssl_https_client_certificate_file;
  std::string ssl_https_client_certificate_type = "PEM";
  std::string ssl_https_private_key_file;
  std::string ssl_https_private_key_type = "PEM";

  // input shape overrides for models with dynamic dims
  // (reference --shape NAME:d1,d2,...; may repeat)
  std::vector<std::pair<std::string, std::vector<int64_t>>> input_shapes;
  // concurrent sequence streams in sequence mode
  // (reference --num-of-sequences, default 4).  When not given
  // explicitly the load manager sizes the slot pool to cover the
  // concurrency level, so distinct workers never share a sequence.
  size_t num_of_sequences = 4;
  bool num_of_sequences_given = false;
  // directory holding per-input raw data files (reference
  // --data-directory; consumed with --input-data style payloads)
  std::string data_directory;
  // gRPC per-message compression: "" | deflate | gzip | none
  // (reference --grpc-compression-algorithm)
  std::string grpc_compression_algorithm;
  // TF-Serving signature (reference --model-signature-name)
  std::string model_signature_name = "serving_default";
  // BLS composing models to report server-side stats for (reference
  // --bls-composing-models; comma-separated)
  std::vector<std::string> bls_composing_models;

  bool usage_requested = false;
};

class CLParser {
 public:
  // Parses argv; returns false (with *error set) on invalid input.
  static bool Parse(
      int argc, char** argv, PerfAnalyzerParameters* params,
      std::string* error);

  static std::string Usage();
};

}  // namespace pa
