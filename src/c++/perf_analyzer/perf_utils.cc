#include "perf_utils.h"

#include <chrono>

namespace pa {

std::atomic<bool> early_exit{false};

uint64_t
NowNs()
{
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

int64_t
ByteSize(const std::string& datatype)
{
  if (datatype == "BOOL" || datatype == "INT8" || datatype == "UINT8") {
    return 1;
  }
  if (datatype == "INT16" || datatype == "UINT16" || datatype == "FP16" ||
      datatype == "BF16") {
    return 2;
  }
  if (datatype == "INT32" || datatype == "UINT32" || datatype == "FP32") {
    return 4;
  }
  if (datatype == "INT64" || datatype == "UINT64" || datatype == "FP64") {
    return 8;
  }
  return -1;  // BYTES
}

int64_t
ElementCount(const std::vector<int64_t>& shape)
{
  int64_t count = 1;
  for (int64_t d : shape) {
    count *= (d < 0 ? 1 : d);
  }
  return count;
}

}  // namespace pa
