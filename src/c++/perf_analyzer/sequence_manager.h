// Sequence-id allocation and per-request sequence flags for stateful
// sequence models (reference sequence_manager.{h,cc}:46-210).

#pragma once

#include <atomic>
#include <cstdint>
#include <mutex>
#include <random>
#include <vector>

namespace pa {

class SequenceManager {
 public:
  // `concurrent` independent sequences; each restarts after
  // `sequence_length` (+- variation pct) requests.  Ids are allocated
  // from `start_id`, wrapping within `id_range` when nonzero (reference
  // --start-sequence-id / --sequence-id-range semantics,
  // reference sequence_manager.cc:46-210).
  SequenceManager(
      size_t concurrent, size_t sequence_length,
      double length_variation_pct = 0.0, uint32_t seed = 33,
      uint64_t start_id = 1, uint64_t id_range = 0)
      : states_(concurrent), base_length_(sequence_length),
        variation_pct_(length_variation_pct), rng_(seed),
        start_id_(start_id == 0 ? 1 : start_id), id_range_(id_range)
  {
    for (size_t i = 0; i < states_.size(); ++i) {
      states_[i].slot = i;
      states_[i].id = NextId(states_[i]);
      states_[i].remaining = DrawLength();
      states_[i].drawn = states_[i].remaining;
    }
  }

  struct Flags {
    uint64_t sequence_id;
    bool start;
    bool end;
  };

  // Advance sequence slot `slot` by one request.
  Flags Next(size_t slot)
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto& st = states_[slot % states_.size()];
    Flags flags;
    flags.start = (st.remaining == DrawnLengthOf(st));
    st.remaining--;
    flags.end = (st.remaining == 0);
    flags.sequence_id = st.id;
    if (flags.end) {
      st.id = NextId(st);
      st.remaining = DrawLength();
      st.drawn = st.remaining;
    }
    return flags;
  }

  // Force-close all open sequences; returns flags for each still-open one
  // (reference CompleteOngoingSequences, concurrency_worker.cc:206-215).
  std::vector<Flags> CompleteOngoing()
  {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<Flags> out;
    for (auto& st : states_) {
      if (st.remaining != DrawnLengthOf(st)) {
        out.push_back({st.id, false, true});
        st.id = NextId(st);
        st.remaining = DrawLength();
        st.drawn = st.remaining;
      }
    }
    return out;
  }

 private:
  struct State {
    size_t slot = 0;
    uint64_t counter = 0;  // sequences this slot has started
    uint64_t id = 0;
    size_t remaining = 0;
    size_t drawn = 0;
  };

  size_t DrawLength()
  {
    if (variation_pct_ <= 0.0) {
      return base_length_;
    }
    double lo = base_length_ * (1.0 - variation_pct_ / 100.0);
    double hi = base_length_ * (1.0 + variation_pct_ / 100.0);
    std::uniform_real_distribution<double> dist(lo, hi);
    size_t len = (size_t)dist(rng_);
    return len == 0 ? 1 : len;
  }

  size_t DrawnLengthOf(const State& st)
  {
    return st.drawn != 0 ? st.drawn : base_length_;
  }

  uint64_t NextId(State& st)
  {
    // Each slot draws from its own residue class modulo the slot count
    // (slot, slot+C, slot+2C, ... within id_range_): the classes are
    // disjoint, so two concurrently-live sequences can never share an
    // id no matter how their lifetimes interleave — a global counter
    // with a plain modulo could hand slot A the id slot B is still
    // using.
    const uint64_t concurrent = states_.size();
    uint64_t lane = st.counter++;
    if (id_range_ > 0) {
      // ids in this slot's class: ceil((id_range_ - slot) / concurrent);
      // direct construction may violate range >= concurrent (the CLI
      // validates it), so clamp the degenerate case
      uint64_t lane_size =
          id_range_ > st.slot
              ? (id_range_ - st.slot + concurrent - 1) / concurrent
              : 1;
      lane %= lane_size;
    }
    return start_id_ + st.slot + lane * concurrent;
  }

  std::mutex mu_;
  std::vector<State> states_;
  size_t base_length_;
  double variation_pct_;
  std::mt19937 rng_;
  uint64_t start_id_ = 1;
  uint64_t id_range_ = 0;
};

}  // namespace pa
