#include "model_parser.h"

namespace pa {

namespace {

// proto3 JSON mapping renders int64 as quoted strings (the gRPC
// backend's MessageToJsonString path); accept both forms.
int64_t
JsonInt(const tc::json::ValuePtr& v)
{
  if (v == nullptr) {
    return 0;
  }
  if (v->type() == tc::json::Type::String) {
    return strtoll(v->AsString().c_str(), nullptr, 10);
  }
  return v->AsInt();
}

std::vector<ModelTensor>
ParseTensors(const tc::json::ValuePtr& arr, bool strip_batch, int max_batch)
{
  std::vector<ModelTensor> out;
  if (arr == nullptr) {
    return out;
  }
  for (const auto& t : arr->Elements()) {
    ModelTensor tensor;
    auto name = t->Get("name");
    auto datatype = t->Get("datatype");
    auto shape = t->Get("shape");
    tensor.name = name ? name->AsString() : "";
    tensor.datatype = datatype ? datatype->AsString() : "FP32";
    if (shape != nullptr) {
      for (const auto& d : shape->Elements()) {
        tensor.shape.push_back(JsonInt(d));
      }
    }
    // metadata shapes include the batch dim for batching models
    if (strip_batch && max_batch > 0 && !tensor.shape.empty()) {
      tensor.shape.erase(tensor.shape.begin());
    }
    out.push_back(std::move(tensor));
  }
  return out;
}

}  // namespace

tc::Error
ModelParser::Init(
    ClientBackend* backend, const std::string& model_name,
    const std::string& model_version)
{
  model_name_ = model_name;
  model_version_ = model_version;

  std::string config_json;
  tc::Error err =
      backend->ModelConfig(&config_json, model_name, model_version);
  if (!err.IsOk()) {
    return err;
  }
  std::string parse_err;
  auto config = tc::json::Parse(config_json, &parse_err);
  if (config == nullptr) {
    return tc::Error("failed to parse model config: " + parse_err);
  }
  auto mbs = config->Get("max_batch_size");
  max_batch_size_ = (int)JsonInt(mbs);
  if (config->Has("ensemble_scheduling")) {
    scheduler_ = SchedulerType::ENSEMBLE;
    // composing models, for per-step server-stat merging (reference
    // inference_profiler.cc:868-1097 ensemble stat handling)
    auto steps = config->Get("ensemble_scheduling")->Get("step");
    if (steps != nullptr) {
      for (const auto& step : steps->Elements()) {
        auto name = step->Get("model_name");
        if (name != nullptr) {
          composing_models_.push_back(name->AsString());
        }
      }
    }
  } else if (config->Has("sequence_batching")) {
    scheduler_ = SchedulerType::SEQUENCE;
  } else if (config->Has("dynamic_batching")) {
    scheduler_ = SchedulerType::DYNAMIC;
  }
  auto txn = config->Get("model_transaction_policy");
  if (txn != nullptr && txn->Get("decoupled") != nullptr) {
    decoupled_ = txn->Get("decoupled")->AsBool();
  }

  std::string metadata_json;
  err = backend->ModelMetadata(&metadata_json, model_name, model_version);
  if (!err.IsOk()) {
    return err;
  }
  auto metadata = tc::json::Parse(metadata_json, &parse_err);
  if (metadata == nullptr) {
    return tc::Error("failed to parse model metadata: " + parse_err);
  }
  inputs_ = ParseTensors(metadata->Get("inputs"), false, max_batch_size_);
  outputs_ = ParseTensors(metadata->Get("outputs"), false, max_batch_size_);
  return tc::Error::Success;
}

}  // namespace pa
