// Health, metadata, config and repository-index queries over HTTP (role
// of reference simple_http_health_metadata.cc).

#include <unistd.h>

#include <iostream>
#include <memory>

#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8000");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose),
      "unable to create http client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server liveness");
  bool ready = false;
  FAIL_IF_ERR(client->IsServerReady(&ready), "server readiness");
  bool model_ready = false;
  FAIL_IF_ERR(
      client->IsModelReady(&model_ready, "simple"), "model readiness");
  if (!live || !ready || !model_ready) {
    std::cerr << "error: server/model not ready" << std::endl;
    exit(1);
  }

  std::string server_metadata;
  FAIL_IF_ERR(client->ServerMetadata(&server_metadata), "server metadata");
  if (server_metadata.find("\"name\"") == std::string::npos) {
    std::cerr << "error: unexpected server metadata" << std::endl;
    exit(1);
  }

  std::string model_metadata;
  FAIL_IF_ERR(
      client->ModelMetadata(&model_metadata, "simple"), "model metadata");
  if (model_metadata.find("\"simple\"") == std::string::npos) {
    std::cerr << "error: unexpected model metadata" << std::endl;
    exit(1);
  }

  std::string model_config;
  FAIL_IF_ERR(
      client->ModelConfig(&model_config, "simple"), "model config");

  std::string index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");
  if (index.find("\"simple\"") == std::string::npos) {
    std::cerr << "error: 'simple' not in repository index" << std::endl;
    exit(1);
  }

  std::cout << "health metadata OK" << std::endl;
  return 0;
}
