// Stateful sequences with synchronous infer over HTTP (role of
// reference simple_http_sequence_sync_infer_client.cc).

#include <unistd.h>

#include <iostream>
#include <memory>
#include <vector>

#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

namespace {

int32_t
Send(
    tc::InferenceServerHttpClient* client, uint64_t sequence_id,
    int32_t value, bool start, bool end)
{
  tc::InferInput* input;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input, "INPUT", {1}, "INT32"),
      "creating INPUT");
  std::shared_ptr<tc::InferInput> input_ptr(input);
  FAIL_IF_ERR(
      input_ptr->AppendRaw((const uint8_t*)&value, sizeof(value)),
      "appending INPUT");
  tc::InferRequestedOutput* output;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output, "OUTPUT"),
      "creating OUTPUT");
  std::shared_ptr<tc::InferRequestedOutput> output_ptr(output);
  tc::InferOptions options("sequence_accumulate");
  options.sequence_id_ = sequence_id;
  options.sequence_start_ = start;
  options.sequence_end_ = end;
  tc::InferResult* result;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, {input_ptr.get()}, {output_ptr.get()}),
      "infer");
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");
  const uint8_t* buf;
  size_t len;
  FAIL_IF_ERR(result_ptr->RawData("OUTPUT", &buf, &len), "OUTPUT data");
  return *(const int32_t*)buf;
}

}  // namespace

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8000");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose),
      "unable to create http client");

  const std::vector<int32_t> values{11, 7, 5, 3, 2, 0, 1};
  const uint64_t seq0 = 7007, seq1 = 7008;
  int32_t acc0 = 0, acc1 = 0;
  for (size_t i = 0; i < values.size(); ++i) {
    bool start = (i == 0);
    bool end = (i == values.size() - 1);
    acc0 = Send(client.get(), seq0, values[i], start, end);
    acc1 = Send(client.get(), seq1, -values[i], start, end);
  }
  int32_t total = 0;
  for (auto v : values) {
    total += v;
  }
  std::cout << "sequence " << seq0 << ": " << acc0 << std::endl;
  std::cout << "sequence " << seq1 << ": " << acc1 << std::endl;
  if (acc0 != total || acc1 != -total) {
    std::cerr << "error: wrong accumulated values" << std::endl;
    exit(1);
  }
  std::cout << "sequence sync OK" << std::endl;
  return 0;
}
