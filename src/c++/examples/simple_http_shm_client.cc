// System shared-memory infer on the `simple` model over HTTP (role of
// reference src/c++/examples/simple_http_shm_client.cc): inputs written
// directly into a POSIX shm region, outputs delivered into another, no
// tensor bytes on the wire.

#include <unistd.h>

#include <cstring>
#include <iostream>

#include "http_client.h"
#include "shm_utils.h"

namespace tc = tc;

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8000");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose),
      "unable to create http client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  // input region holds INPUT0 then INPUT1; output region OUTPUT0, OUTPUT1
  const char* kInputKey = "/simple_http_shm_input";
  const char* kOutputKey = "/simple_http_shm_output";
  client->UnregisterSystemSharedMemory("simple_input");
  client->UnregisterSystemSharedMemory("simple_output");

  int input_fd, output_fd;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(kInputKey, 2 * kTensorBytes, &input_fd),
      "creating input region");
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(
          kOutputKey, 2 * kTensorBytes, &output_fd),
      "creating output region");
  void* input_base;
  void* output_base;
  FAIL_IF_ERR(
      tc::MapSharedMemory(input_fd, 0, 2 * kTensorBytes, &input_base),
      "mapping input region");
  FAIL_IF_ERR(
      tc::MapSharedMemory(output_fd, 0, 2 * kTensorBytes, &output_base),
      "mapping output region");

  int32_t* input0_data = reinterpret_cast<int32_t*>(input_base);
  int32_t* input1_data = input0_data + 16;
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
    input1_data[i] = 1;
  }

  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "simple_input", kInputKey, 2 * kTensorBytes),
      "registering input region");
  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "simple_output", kOutputKey, 2 * kTensorBytes),
      "registering output region");

  tc::InferInput* input0;
  tc::InferInput* input1;
  std::vector<int64_t> shape{1, 16};
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", shape, "INT32"),
      "creating INPUT0");
  std::shared_ptr<tc::InferInput> input0_ptr(input0);
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", shape, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input1_ptr(input1);
  FAIL_IF_ERR(
      input0_ptr->SetSharedMemory("simple_input", kTensorBytes, 0),
      "INPUT0 shm");
  FAIL_IF_ERR(
      input1_ptr->SetSharedMemory(
          "simple_input", kTensorBytes, kTensorBytes),
      "INPUT1 shm");

  tc::InferRequestedOutput* output0;
  tc::InferRequestedOutput* output1;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0, "OUTPUT0"), "OUTPUT0");
  std::shared_ptr<tc::InferRequestedOutput> output0_ptr(output0);
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output1, "OUTPUT1"), "OUTPUT1");
  std::shared_ptr<tc::InferRequestedOutput> output1_ptr(output1);
  FAIL_IF_ERR(
      output0_ptr->SetSharedMemory("simple_output", kTensorBytes, 0),
      "OUTPUT0 shm");
  FAIL_IF_ERR(
      output1_ptr->SetSharedMemory(
          "simple_output", kTensorBytes, kTensorBytes),
      "OUTPUT1 shm");

  tc::InferOptions options("simple");
  std::vector<tc::InferInput*> inputs = {input0_ptr.get(),
                                         input1_ptr.get()};
  std::vector<const tc::InferRequestedOutput*> outputs = {
      output0_ptr.get(), output1_ptr.get()};

  tc::InferResult* result;
  FAIL_IF_ERR(client->Infer(&result, options, inputs, outputs), "infer");
  FAIL_IF_ERR(result->RequestStatus(), "inference failed");
  delete result;

  int32_t* sum = reinterpret_cast<int32_t*>(output_base);
  int32_t* diff = sum + 16;
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != input0_data[i] + input1_data[i] ||
        diff[i] != input0_data[i] - input1_data[i]) {
      std::cerr << "error: incorrect shm result at " << i << std::endl;
      exit(1);
    }
  }
  std::cout << "shm infer OK" << std::endl;

  FAIL_IF_ERR(
      client->UnregisterSystemSharedMemory("simple_input"),
      "unregister input");
  FAIL_IF_ERR(
      client->UnregisterSystemSharedMemory("simple_output"),
      "unregister output");
  tc::UnmapSharedMemory(input_base, 2 * kTensorBytes);
  tc::UnmapSharedMemory(output_base, 2 * kTensorBytes);
  tc::CloseSharedMemory(input_fd);
  tc::CloseSharedMemory(output_fd);
  tc::UnlinkSharedMemoryRegion(kInputKey);
  tc::UnlinkSharedMemoryRegion(kOutputKey);
  return 0;
}
