// Reuse InferInput / InferRequestedOutput / client objects across many
// requests on both protocols — the allocation-free steady-state pattern
// (role of reference src/c++/examples/reuse_infer_objects_client.cc).

#include <unistd.h>

#include <iostream>
#include <memory>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

namespace {

void
Validate(
    tc::InferResult* result, const std::vector<int32_t>& in0,
    const std::vector<int32_t>& in1)
{
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");
  const uint8_t* buf;
  size_t len;
  FAIL_IF_ERR(result_ptr->RawData("OUTPUT0", &buf, &len), "OUTPUT0");
  const int32_t* sums = (const int32_t*)buf;
  for (size_t i = 0; i < in0.size(); ++i) {
    if (sums[i] != in0[i] + in1[i]) {
      std::cerr << "error: incorrect sum at " << i << std::endl;
      exit(1);
    }
  }
}

}  // namespace

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string http_url("localhost:8000");
  std::string grpc_url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:g:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        http_url = optarg;
        break;
      case 'g':
        grpc_url = optarg;
        break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-v] [-u http_url] [-g grpc_url]" << std::endl;
        exit(1);
    }
  }

  std::vector<int32_t> input0_data(16), input1_data(16, 1);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
  }

  // objects created once, reused for every request below
  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  tc::InferRequestedOutput* output0;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0, "OUTPUT0"),
      "creating OUTPUT0");
  std::shared_ptr<tc::InferRequestedOutput> output0_ptr(output0);
  tc::InferOptions options("simple");

  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&http_client, http_url,
                                            verbose),
      "creating http client");
  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&grpc_client, grpc_url,
                                            verbose),
      "creating grpc client");

  for (int iteration = 0; iteration < 4; ++iteration) {
    for (auto& v : input0_data) {
      v += iteration;
    }
    // Reset + refill the same input objects
    FAIL_IF_ERR(input0_ptr->Reset(), "resetting INPUT0");
    FAIL_IF_ERR(input1_ptr->Reset(), "resetting INPUT1");
    FAIL_IF_ERR(
        input0_ptr->AppendRaw(
            (const uint8_t*)input0_data.data(),
            input0_data.size() * sizeof(int32_t)),
        "INPUT0 data");
    FAIL_IF_ERR(
        input1_ptr->AppendRaw(
            (const uint8_t*)input1_data.data(),
            input1_data.size() * sizeof(int32_t)),
        "INPUT1 data");

    tc::InferResult* result = nullptr;
    FAIL_IF_ERR(
        http_client->Infer(
            &result, options, {input0_ptr.get(), input1_ptr.get()},
            {output0_ptr.get()}),
        "http infer");
    Validate(result, input0_data, input1_data);

    result = nullptr;
    FAIL_IF_ERR(
        grpc_client->Infer(
            &result, options, {input0_ptr.get(), input1_ptr.get()},
            {output0_ptr.get()}),
        "grpc infer");
    Validate(result, input0_data, input1_data);
  }

  std::cout << "reuse infer objects OK" << std::endl;
  return 0;
}
