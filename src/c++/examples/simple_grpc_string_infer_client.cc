// BYTES-tensor infer on `simple_string` over gRPC (role of reference
// simple_grpc_string_infer_client.cc).

#include <unistd.h>

#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  std::vector<std::string> input0_data, input1_data;
  for (int i = 0; i < 16; ++i) {
    input0_data.push_back(std::to_string(i));
    input1_data.push_back("1");
  }

  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "BYTES"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "BYTES"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  FAIL_IF_ERR(input0_ptr->AppendFromString(input0_data), "INPUT0 data");
  FAIL_IF_ERR(input1_ptr->AppendFromString(input1_data), "INPUT1 data");

  tc::InferOptions options("simple_string");
  tc::InferResult* result;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, {input0_ptr.get(), input1_ptr.get()}),
      "infer");
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");

  std::vector<std::string> sums, diffs;
  FAIL_IF_ERR(result_ptr->StringData("OUTPUT0", &sums), "OUTPUT0 data");
  FAIL_IF_ERR(result_ptr->StringData("OUTPUT1", &diffs), "OUTPUT1 data");
  for (int i = 0; i < 16; ++i) {
    if (std::stoi(sums[i]) != i + 1) {
      std::cerr << "error: incorrect sum at " << i << std::endl;
      exit(1);
    }
    if (std::stoi(diffs[i]) != i - 1) {
      std::cerr << "error: incorrect difference at " << i << std::endl;
      exit(1);
    }
  }
  std::cout << "string infer OK" << std::endl;
  return 0;
}
