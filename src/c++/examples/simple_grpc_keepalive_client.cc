// Client keepalive demo: configures KeepAliveOptions so the channel
// pings the server on an interval, then proves the pings flow (role of
// reference src/c++/examples/simple_grpc_keepalive_client.cc).  On this
// stack keepalive rides h2 PING frames (grpc_client.h KeepAliveOptions);
// the ping counter only advances on server-acknowledged round-trips, so
// a nonzero count is an end-to-end liveness proof.
//
// Usage: simple_grpc_keepalive_client [-v] [-u host:port] [-t time_ms]

#include <unistd.h>

#include <chrono>
#include <iostream>
#include <thread>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int keepalive_time_ms = 50;

  int opt;
  while ((opt = getopt(argc, argv, "vu:t:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      case 't':
        keepalive_time_ms = atoi(optarg);
        break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-v] [-u host:port] [-t time_ms]" << std::endl;
        exit(1);
    }
  }

  tc::KeepAliveOptions keepalive;
  keepalive.keepalive_time_ms = keepalive_time_ms;
  keepalive.keepalive_timeout_ms = 5000;
  keepalive.keepalive_permit_without_calls = true;
  keepalive.http2_max_pings_without_data = 0;  // 0 = unlimited (gRPC semantics)

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(
          &client, url, verbose, /*use_ssl=*/false, tc::SslOptions(),
          keepalive),
      "unable to create grpc client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server liveness");
  std::cout << "server live: " << live << std::endl;

  // idle while the keepalive worker pings
  std::this_thread::sleep_for(
      std::chrono::milliseconds(keepalive_time_ms * 6));

  const uint64_t pings = client->KeepAlivePingCount();
  std::cout << "keepalive pings acknowledged: " << pings << std::endl;

  // the connection must still be usable after idling
  bool ready = false;
  FAIL_IF_ERR(client->IsModelReady(&ready, "simple"), "model readiness");
  std::cout << "model ready after idle: " << ready << std::endl;

  if (pings == 0) {
    std::cerr << "error: no keepalive pings observed" << std::endl;
    return 1;
  }
  std::cout << "keepalive OK" << std::endl;
  return 0;
}
