// System shared-memory infer on the `simple` model over gRPC (role of
// reference src/c++/examples/simple_grpc_shm_client.cc).

#include <unistd.h>

#include <cstring>
#include <iostream>

#include "grpc_client.h"
#include "shm_utils.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  const char* kInputKey = "/simple_grpc_shm_input";
  const char* kOutputKey = "/simple_grpc_shm_output";
  client->UnregisterSystemSharedMemory("simple_input");
  client->UnregisterSystemSharedMemory("simple_output");

  int input_fd, output_fd;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(kInputKey, 2 * kTensorBytes, &input_fd),
      "creating input region");
  void* input_base;
  FAIL_IF_ERR(
      tc::MapSharedMemory(input_fd, 0, 2 * kTensorBytes, &input_base),
      "mapping input region");
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(kOutputKey, 2 * kTensorBytes, &output_fd),
      "creating output region");
  void* output_base;
  FAIL_IF_ERR(
      tc::MapSharedMemory(output_fd, 0, 2 * kTensorBytes, &output_base),
      "mapping output region");

  int32_t* input_data = (int32_t*)input_base;
  for (int i = 0; i < 16; ++i) {
    input_data[i] = i;
    input_data[16 + i] = 1;
  }

  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "simple_input", kInputKey, 2 * kTensorBytes),
      "registering input region");
  FAIL_IF_ERR(
      client->RegisterSystemSharedMemory(
          "simple_output", kOutputKey, 2 * kTensorBytes),
      "registering output region");

  inference::SystemSharedMemoryStatusResponse status;
  FAIL_IF_ERR(client->SystemSharedMemoryStatus(&status), "shm status");
  if (status.regions_size() < 2) {
    std::cerr << "error: expected 2 registered regions" << std::endl;
    exit(1);
  }

  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  FAIL_IF_ERR(
      input0_ptr->SetSharedMemory("simple_input", kTensorBytes, 0),
      "INPUT0 shm");
  FAIL_IF_ERR(
      input1_ptr->SetSharedMemory(
          "simple_input", kTensorBytes, kTensorBytes),
      "INPUT1 shm");

  tc::InferRequestedOutput* output0;
  tc::InferRequestedOutput* output1;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0, "OUTPUT0"),
      "creating OUTPUT0");
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output1, "OUTPUT1"),
      "creating OUTPUT1");
  std::shared_ptr<tc::InferRequestedOutput> output0_ptr(output0),
      output1_ptr(output1);
  FAIL_IF_ERR(
      output0_ptr->SetSharedMemory("simple_output", kTensorBytes, 0),
      "OUTPUT0 shm");
  FAIL_IF_ERR(
      output1_ptr->SetSharedMemory(
          "simple_output", kTensorBytes, kTensorBytes),
      "OUTPUT1 shm");

  tc::InferOptions options("simple");
  tc::InferResult* result;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, {input0_ptr.get(), input1_ptr.get()},
          {output0_ptr.get(), output1_ptr.get()}),
      "infer");
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");

  int32_t* output_data = (int32_t*)output_base;
  for (int i = 0; i < 16; ++i) {
    if (output_data[i] != input_data[i] + input_data[16 + i]) {
      std::cerr << "error: incorrect sum at " << i << std::endl;
      exit(1);
    }
    if (output_data[16 + i] != input_data[i] - input_data[16 + i]) {
      std::cerr << "error: incorrect difference at " << i << std::endl;
      exit(1);
    }
  }

  client->UnregisterSystemSharedMemory("simple_input");
  client->UnregisterSystemSharedMemory("simple_output");
  tc::UnmapSharedMemory(input_base, 2 * kTensorBytes);
  tc::UnmapSharedMemory(output_base, 2 * kTensorBytes);
  tc::CloseSharedMemory(input_fd);
  tc::CloseSharedMemory(output_fd);
  tc::UnlinkSharedMemoryRegion(kInputKey);
  tc::UnlinkSharedMemoryRegion(kOutputKey);

  std::cout << "shm infer OK" << std::endl;
  return 0;
}
