// Asynchronous infer on the `simple` add/sub model over HTTP: several
// requests issued without waiting, completions collected via callback
// (role of reference src/c++/examples/simple_http_async_infer_client.cc).

#include <unistd.h>

#include <condition_variable>
#include <iostream>
#include <memory>
#include <mutex>
#include <vector>

#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8000");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose, 4),
      "unable to create http client");

  std::vector<int32_t> input0_data(16), input1_data(16, 2);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
  }
  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  input0_ptr->AppendRaw(
      (const uint8_t*)input0_data.data(),
      input0_data.size() * sizeof(int32_t));
  input1_ptr->AppendRaw(
      (const uint8_t*)input1_data.data(),
      input1_data.size() * sizeof(int32_t));
  tc::InferRequestedOutput* output0;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0, "OUTPUT0"),
      "creating OUTPUT0");
  std::shared_ptr<tc::InferRequestedOutput> output0_ptr(output0);
  tc::InferOptions options("simple");

  constexpr int kRequests = 8;
  std::mutex mu;
  std::condition_variable cv;
  int completed = 0;
  bool failed = false;
  for (int r = 0; r < kRequests; ++r) {
    FAIL_IF_ERR(
        client->AsyncInfer(
            [&](tc::InferResult* result) {
              std::unique_ptr<tc::InferResult> result_ptr(result);
              bool ok = result_ptr->RequestStatus().IsOk();
              const uint8_t* buf;
              size_t len;
              if (ok &&
                  result_ptr->RawData("OUTPUT0", &buf, &len).IsOk()) {
                const int32_t* sums = (const int32_t*)buf;
                for (int i = 0; i < 16; ++i) {
                  if (sums[i] != i + 2) {
                    ok = false;
                  }
                }
              } else {
                ok = false;
              }
              std::lock_guard<std::mutex> lk(mu);
              if (!ok) {
                failed = true;
              }
              ++completed;
              cv.notify_all();
            },
            options, {input0_ptr.get(), input1_ptr.get()},
            {output0_ptr.get()}),
        "async infer");
  }
  {
    std::unique_lock<std::mutex> lk(mu);
    if (!cv.wait_for(lk, std::chrono::seconds(60), [&] {
          return completed == kRequests;
        })) {
      std::cerr << "error: timed out waiting for completions" << std::endl;
      exit(1);
    }
  }
  if (failed) {
    std::cerr << "error: a request returned a wrong result" << std::endl;
    exit(1);
  }
  std::cout << "async infer OK" << std::endl;
  return 0;
}
