// Stateful sequences over the gRPC bidi stream: two interleaved
// sequences on one stream against `sequence_accumulate` (role of
// reference simple_grpc_sequence_stream_infer_client.cc).

#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <iostream>
#include <map>
#include <mutex>
#include <vector>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  const std::vector<int32_t> values{11, 7, 5, 3, 2, 0, 1};
  const uint64_t seq0 = 5007, seq1 = 5008;
  const size_t expected_total = values.size() * 2;

  std::mutex mu;
  std::condition_variable cv;
  size_t received = 0;
  std::map<std::string, int32_t> results;
  FAIL_IF_ERR(
      client->StartStream([&](tc::InferResult* result) {
        std::unique_ptr<tc::InferResult> result_ptr(result);
        if (result_ptr->RequestStatus().IsOk()) {
          std::string id;
          result_ptr->Id(&id);
          const uint8_t* buf;
          size_t len;
          result_ptr->RawData("OUTPUT", &buf, &len);
          std::lock_guard<std::mutex> lk(mu);
          results[id] = *(const int32_t*)buf;
        }
        {
          std::lock_guard<std::mutex> lk(mu);
          ++received;
        }
        cv.notify_all();
      }),
      "starting stream");

  for (size_t i = 0; i < values.size(); ++i) {
    for (auto& seq : std::vector<std::pair<uint64_t, int32_t>>{
             {seq0, values[i]}, {seq1, -values[i]}}) {
      tc::InferInput* input;
      FAIL_IF_ERR(
          tc::InferInput::Create(&input, "INPUT", {1}, "INT32"),
          "creating INPUT");
      std::shared_ptr<tc::InferInput> input_ptr(input);
      FAIL_IF_ERR(
          input_ptr->AppendRaw(
              (const uint8_t*)&seq.second, sizeof(int32_t)),
          "appending INPUT");
      tc::InferOptions options("sequence_accumulate");
      options.sequence_id_ = seq.first;
      options.sequence_start_ = (i == 0);
      options.sequence_end_ = (i == values.size() - 1);
      options.request_id_ =
          std::to_string(seq.first) + "_" + std::to_string(i);
      FAIL_IF_ERR(
          client->AsyncStreamInfer(options, {input_ptr.get()}),
          "stream infer");
    }
  }

  {
    std::unique_lock<std::mutex> lk(mu);
    if (!cv.wait_for(lk, std::chrono::seconds(30), [&] {
          return received >= expected_total;
        })) {
      std::cerr << "error: timed out waiting for stream responses"
                << std::endl;
      exit(1);
    }
  }
  FAIL_IF_ERR(client->StopStream(), "stopping stream");

  int32_t total = 0;
  for (auto v : values) {
    total += v;
  }
  const std::string last = "_" + std::to_string(values.size() - 1);
  if (results[std::to_string(seq0) + last] != total ||
      results[std::to_string(seq1) + last] != -total) {
    std::cerr << "error: wrong accumulated values" << std::endl;
    exit(1);
  }
  std::cout << "sequence " << seq0 << ": " << total << std::endl;
  std::cout << "sequence " << seq1 << ": " << -total << std::endl;
  std::cout << "sequence stream OK" << std::endl;
  return 0;
}
