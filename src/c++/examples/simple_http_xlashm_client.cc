// TPU (XLA) shared-memory infer on the `simple` model over HTTP — the
// TPU-native role of reference simple_http_cudashm_client.cc (the
// cudaMalloc → cudaIpc handle → RegisterCudaSharedMemory →
// SetSharedMemory scenario, which the reference ships over BOTH
// protocols).  This process creates the region's host staging window,
// serializes an XlaShmHandle-compatible raw handle
// {uuid, shm_key, byte_size, device_ordinal}, and registers it through
// the XLA plane's HTTP verbs; the server stages tensors to TPU HBM on
// use (tritonclient/utils/xla_shared_memory).

#include <unistd.h>

#include <cstring>
#include <iostream>
#include <string>

#include "http_client.h"
#include "shm_utils.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

namespace {

std::string
Base64Encode(const std::string& in)
{
  static const char tbl[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  size_t i = 0;
  while (i + 2 < in.size()) {
    uint32_t v = ((uint8_t)in[i] << 16) | ((uint8_t)in[i + 1] << 8) |
                 (uint8_t)in[i + 2];
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += tbl[v & 63];
    i += 3;
  }
  if (i + 1 == in.size()) {
    uint32_t v = (uint8_t)in[i] << 16;
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += "==";
  } else if (i + 2 == in.size()) {
    uint32_t v = ((uint8_t)in[i] << 16) | ((uint8_t)in[i + 1] << 8);
    out += tbl[(v >> 18) & 63];
    out += tbl[(v >> 12) & 63];
    out += tbl[(v >> 6) & 63];
    out += "=";
  }
  return out;
}

std::string
XlaRawHandle(const std::string& shm_key, size_t byte_size, int device)
{
  std::string json = std::string("{\"uuid\": \"xlashm_http_example") +
                     std::to_string(getpid()) + "\", \"shm_key\": \"" +
                     shm_key +
                     "\", \"byte_size\": " + std::to_string(byte_size) +
                     ", \"device_ordinal\": " + std::to_string(device) + "}";
  return Base64Encode(json);
}

}  // namespace

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8000");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        std::cerr << "usage: " << argv[0] << " [-v] [-u host:port]"
                  << std::endl;
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose),
      "unable to create http client");

  constexpr size_t kTensorBytes = 16 * sizeof(int32_t);
  const char* kInputKey = "/simple_http_xlashm_input";
  const char* kOutputKey = "/simple_http_xlashm_output";
  client->UnregisterXlaSharedMemory("xla_input_data");
  client->UnregisterXlaSharedMemory("xla_output_data");

  // host staging windows for the two regions
  int input_fd, output_fd;
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(kInputKey, 2 * kTensorBytes, &input_fd),
      "creating input window");
  void* input_base;
  FAIL_IF_ERR(
      tc::MapSharedMemory(input_fd, 0, 2 * kTensorBytes, &input_base),
      "mapping input window");
  FAIL_IF_ERR(
      tc::CreateSharedMemoryRegion(kOutputKey, 2 * kTensorBytes, &output_fd),
      "creating output window");
  void* output_base;
  FAIL_IF_ERR(
      tc::MapSharedMemory(output_fd, 0, 2 * kTensorBytes, &output_base),
      "mapping output window");

  int32_t* input_data = (int32_t*)input_base;
  for (int i = 0; i < 16; ++i) {
    input_data[i] = i;       // INPUT0
    input_data[16 + i] = 1;  // INPUT1
  }

  FAIL_IF_ERR(
      client->RegisterXlaSharedMemory(
          "xla_input_data", XlaRawHandle(kInputKey, 2 * kTensorBytes, 0),
          2 * kTensorBytes, 0),
      "registering input region");
  FAIL_IF_ERR(
      client->RegisterXlaSharedMemory(
          "xla_output_data", XlaRawHandle(kOutputKey, 2 * kTensorBytes, 0),
          2 * kTensorBytes, 0),
      "registering output region");

  std::string status;
  FAIL_IF_ERR(client->XlaSharedMemoryStatus(&status), "xla shm status");
  if (status.find("xla_input_data") == std::string::npos ||
      status.find("xla_output_data") == std::string::npos) {
    std::cerr << "error: expected both registered xla regions in status"
              << std::endl;
    exit(1);
  }

  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  FAIL_IF_ERR(
      input0_ptr->SetSharedMemory("xla_input_data", kTensorBytes, 0),
      "INPUT0 shm");
  FAIL_IF_ERR(
      input1_ptr->SetSharedMemory(
          "xla_input_data", kTensorBytes, kTensorBytes),
      "INPUT1 shm");

  tc::InferRequestedOutput* output0;
  tc::InferRequestedOutput* output1;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0, "OUTPUT0"),
      "creating OUTPUT0");
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output1, "OUTPUT1"),
      "creating OUTPUT1");
  std::shared_ptr<tc::InferRequestedOutput> output0_ptr(output0),
      output1_ptr(output1);
  FAIL_IF_ERR(
      output0_ptr->SetSharedMemory("xla_output_data", kTensorBytes, 0),
      "OUTPUT0 shm");
  FAIL_IF_ERR(
      output1_ptr->SetSharedMemory(
          "xla_output_data", kTensorBytes, kTensorBytes),
      "OUTPUT1 shm");

  tc::InferOptions options("simple");
  tc::InferResult* result;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, {input0_ptr.get(), input1_ptr.get()},
          {output0_ptr.get(), output1_ptr.get()}),
      "infer");
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");

  // outputs land in the output window (server syncs the region's host
  // view on write-back for cross-process clients)
  int32_t* output_data = (int32_t*)output_base;
  for (int i = 0; i < 16; ++i) {
    if (output_data[i] != input_data[i] + input_data[16 + i]) {
      std::cerr << "error: incorrect sum at " << i << std::endl;
      exit(1);
    }
    if (output_data[16 + i] != input_data[i] - input_data[16 + i]) {
      std::cerr << "error: incorrect difference at " << i << std::endl;
      exit(1);
    }
  }

  FAIL_IF_ERR(
      client->UnregisterXlaSharedMemory("xla_input_data"),
      "unregister input");
  FAIL_IF_ERR(
      client->UnregisterXlaSharedMemory("xla_output_data"),
      "unregister output");
  tc::UnmapSharedMemory(input_base, 2 * kTensorBytes);
  tc::UnmapSharedMemory(output_base, 2 * kTensorBytes);
  tc::CloseSharedMemory(input_fd);
  tc::CloseSharedMemory(output_fd);
  tc::UnlinkSharedMemoryRegion(kInputKey);
  tc::UnlinkSharedMemoryRegion(kOutputKey);

  std::cout << "xla shm infer OK" << std::endl;
  return 0;
}
