// Decoupled bidirectional streaming: one request to the repeat_int32
// model yields N streamed responses (role of reference
// src/c++/examples/simple_grpc_custom_repeat.cc).
//
// Usage: simple_grpc_custom_repeat [-v] [-u host:port] [-r repeat_count]

#include <unistd.h>

#include <condition_variable>
#include <iostream>
#include <mutex>
#include <vector>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int repeat_count = 8;

  int opt;
  while ((opt = getopt(argc, argv, "vu:r:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      case 'r':
        repeat_count = atoi(optarg);
        break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-v] [-u host:port] [-r repeat_count]" << std::endl;
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  // collect streamed responses
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> received;
  FAIL_IF_ERR(
      client->StartStream([&](tc::InferResult* result) {
        tc::Error status = result->RequestStatus();
        if (!status.IsOk()) {
          std::cerr << "error: stream response: " << status << std::endl;
          delete result;
          exit(1);
        }
        const uint8_t* buf;
        size_t byte_size;
        FAIL_IF_ERR(result->RawData("OUT", &buf, &byte_size), "OUT data");
        {
          std::lock_guard<std::mutex> lk(mu);
          received.push_back(*reinterpret_cast<const int32_t*>(buf));
        }
        cv.notify_all();
        delete result;
      }),
      "starting stream");

  std::vector<int32_t> in_data(repeat_count);
  std::vector<uint32_t> delay_data(repeat_count);
  for (int i = 0; i < repeat_count; ++i) {
    in_data[i] = i;
    delay_data[i] = 1000;  // 1 ms between responses
  }
  uint32_t wait_data = 500;

  tc::InferInput* in;
  tc::InferInput* delay;
  tc::InferInput* wait;
  FAIL_IF_ERR(
      tc::InferInput::Create(&in, "IN", {repeat_count}, "INT32"),
      "creating IN");
  std::shared_ptr<tc::InferInput> in_ptr(in);
  FAIL_IF_ERR(
      tc::InferInput::Create(&delay, "DELAY", {repeat_count}, "UINT32"),
      "creating DELAY");
  std::shared_ptr<tc::InferInput> delay_ptr(delay);
  FAIL_IF_ERR(
      tc::InferInput::Create(&wait, "WAIT", {1}, "UINT32"), "creating WAIT");
  std::shared_ptr<tc::InferInput> wait_ptr(wait);

  FAIL_IF_ERR(
      in_ptr->AppendRaw(
          reinterpret_cast<uint8_t*>(in_data.data()),
          in_data.size() * sizeof(int32_t)),
      "setting IN");
  FAIL_IF_ERR(
      delay_ptr->AppendRaw(
          reinterpret_cast<uint8_t*>(delay_data.data()),
          delay_data.size() * sizeof(uint32_t)),
      "setting DELAY");
  FAIL_IF_ERR(
      wait_ptr->AppendRaw(
          reinterpret_cast<uint8_t*>(&wait_data), sizeof(uint32_t)),
      "setting WAIT");

  tc::InferOptions options("repeat_int32");
  std::vector<tc::InferInput*> inputs = {in_ptr.get(), delay_ptr.get(),
                                         wait_ptr.get()};

  FAIL_IF_ERR(
      client->AsyncStreamInfer(options, inputs), "stream infer request");

  {
    std::unique_lock<std::mutex> lk(mu);
    if (!cv.wait_for(lk, std::chrono::seconds(30), [&]() {
          return received.size() >= (size_t)repeat_count;
        })) {
      std::cerr << "error: timed out waiting for " << repeat_count
                << " responses (got " << received.size() << ")" << std::endl;
      exit(1);
    }
  }

  FAIL_IF_ERR(client->StopStream(), "stopping stream");

  for (int i = 0; i < repeat_count; ++i) {
    if (received[i] != in_data[i]) {
      std::cerr << "error: response " << i << " = " << received[i]
                << ", expected " << in_data[i] << std::endl;
      exit(1);
    }
  }
  std::cout << "stream infer OK: " << received.size() << " responses"
            << std::endl;
  return 0;
}
