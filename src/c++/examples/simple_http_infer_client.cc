// Sync + async infer on the `simple` add/sub model over HTTP
// (role of reference src/c++/examples/simple_http_infer_client.cc).
//
// Usage: simple_http_infer_client [-v] [-u host:port]

#include <unistd.h>

#include <condition_variable>
#include <iostream>
#include <mutex>

#include "http_client.h"

namespace tc = tc;

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8000");

  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        std::cerr << "usage: " << argv[0] << " [-v] [-u host:port]"
                  << std::endl;
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerHttpClient::Create(&client, url, verbose),
      "unable to create http client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server liveness");
  if (!live) {
    std::cerr << "error: server is not live" << std::endl;
    exit(1);
  }

  // inputs: two INT32[1,16]
  std::vector<int32_t> input0_data(16);
  std::vector<int32_t> input1_data(16);
  for (size_t i = 0; i < 16; ++i) {
    input0_data[i] = (int32_t)i;
    input1_data[i] = 1;
  }

  tc::InferInput* input0;
  tc::InferInput* input1;
  std::vector<int64_t> shape{1, 16};
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", shape, "INT32"),
      "creating INPUT0");
  std::shared_ptr<tc::InferInput> input0_ptr(input0);
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", shape, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input1_ptr(input1);

  FAIL_IF_ERR(
      input0_ptr->AppendRaw(
          reinterpret_cast<uint8_t*>(input0_data.data()),
          input0_data.size() * sizeof(int32_t)),
      "setting INPUT0 data");
  FAIL_IF_ERR(
      input1_ptr->AppendRaw(
          reinterpret_cast<uint8_t*>(input1_data.data()),
          input1_data.size() * sizeof(int32_t)),
      "setting INPUT1 data");

  tc::InferRequestedOutput* output0;
  tc::InferRequestedOutput* output1;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output0, "OUTPUT0"),
      "creating OUTPUT0");
  std::shared_ptr<tc::InferRequestedOutput> output0_ptr(output0);
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output1, "OUTPUT1"),
      "creating OUTPUT1");
  std::shared_ptr<tc::InferRequestedOutput> output1_ptr(output1);

  tc::InferOptions options("simple");
  std::vector<tc::InferInput*> inputs = {input0_ptr.get(),
                                         input1_ptr.get()};
  std::vector<const tc::InferRequestedOutput*> outputs = {
      output0_ptr.get(), output1_ptr.get()};

  auto validate = [&](tc::InferResult* result) {
    FAIL_IF_ERR(result->RequestStatus(), "inference failed");
    const uint8_t* buf;
    size_t byte_size;
    FAIL_IF_ERR(result->RawData("OUTPUT0", &buf, &byte_size),
                "OUTPUT0 raw data");
    const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
    FAIL_IF_ERR(result->RawData("OUTPUT1", &buf, &byte_size),
                "OUTPUT1 raw data");
    const int32_t* diff = reinterpret_cast<const int32_t*>(buf);
    for (size_t i = 0; i < 16; ++i) {
      if (sum[i] != input0_data[i] + input1_data[i] ||
          diff[i] != input0_data[i] - input1_data[i]) {
        std::cerr << "error: incorrect result at " << i << std::endl;
        exit(1);
      }
    }
  };

  // sync
  tc::InferResult* result;
  FAIL_IF_ERR(
      client->Infer(&result, options, inputs, outputs), "sync infer");
  validate(result);
  delete result;
  std::cout << "sync infer OK" << std::endl;

  // gzip request body + deflate-compressed response
  result = nullptr;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, inputs, outputs, "gzip", "deflate"),
      "compressed infer");
  validate(result);
  delete result;
  std::cout << "compressed infer OK" << std::endl;

  // async
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  FAIL_IF_ERR(
      client->AsyncInfer(
          [&](tc::InferResult* result) {
            validate(result);
            delete result;
            {
              std::lock_guard<std::mutex> lk(mu);
              done = true;
            }
            cv.notify_one();
          },
          options, inputs, outputs),
      "async infer");
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }
  std::cout << "async infer OK" << std::endl;

  tc::InferStat stat;
  client->ClientInferStat(&stat);
  std::cout << "completed " << stat.completed_request_count
            << " requests" << std::endl;
  return 0;
}
