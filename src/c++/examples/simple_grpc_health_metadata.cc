// Health, metadata, config, repository-index and statistics queries over
// gRPC (role of reference simple_grpc_health_metadata.cc).

#include <unistd.h>

#include <iostream>
#include <memory>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  bool live = false;
  FAIL_IF_ERR(client->IsServerLive(&live), "server liveness");
  bool ready = false;
  FAIL_IF_ERR(client->IsServerReady(&ready), "server readiness");
  bool model_ready = false;
  FAIL_IF_ERR(
      client->IsModelReady(&model_ready, "simple"), "model readiness");
  if (!live || !ready || !model_ready) {
    std::cerr << "error: server/model not ready" << std::endl;
    exit(1);
  }

  inference::ServerMetadataResponse server_metadata;
  FAIL_IF_ERR(client->ServerMetadata(&server_metadata), "server metadata");
  std::cout << "server: " << server_metadata.name() << " "
            << server_metadata.version() << std::endl;

  inference::ModelMetadataResponse model_metadata;
  FAIL_IF_ERR(
      client->ModelMetadata(&model_metadata, "simple"), "model metadata");
  if (model_metadata.name() != "simple" ||
      model_metadata.inputs_size() != 2) {
    std::cerr << "error: unexpected model metadata" << std::endl;
    exit(1);
  }

  inference::ModelConfigResponse model_config;
  FAIL_IF_ERR(
      client->ModelConfig(&model_config, "simple"), "model config");
  if (model_config.config().name() != "simple") {
    std::cerr << "error: unexpected model config" << std::endl;
    exit(1);
  }

  inference::RepositoryIndexResponse index;
  FAIL_IF_ERR(client->ModelRepositoryIndex(&index), "repository index");
  bool found = false;
  for (const auto& m : index.models()) {
    if (m.name() == "simple") {
      found = true;
    }
  }
  if (!found) {
    std::cerr << "error: 'simple' not in repository index" << std::endl;
    exit(1);
  }

  inference::ModelStatisticsResponse stats;
  FAIL_IF_ERR(
      client->ModelInferenceStatistics(&stats, "simple"), "statistics");

  std::cout << "health metadata OK" << std::endl;
  return 0;
}
