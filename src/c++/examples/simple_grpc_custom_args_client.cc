// Infer with custom request id, priority and per-request options (role
// of reference simple_grpc_custom_args_client.cc).

#include <unistd.h>

#include <iostream>
#include <memory>
#include <vector>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  std::vector<int32_t> input0_data(16), input1_data(16, 4);
  for (int i = 0; i < 16; ++i) {
    input0_data[i] = i;
  }
  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  input0_ptr->AppendRaw(
      (const uint8_t*)input0_data.data(),
      input0_data.size() * sizeof(int32_t));
  input1_ptr->AppendRaw(
      (const uint8_t*)input1_data.data(),
      input1_data.size() * sizeof(int32_t));

  tc::InferOptions options("simple");
  options.request_id_ = "custom-args-1";
  options.priority_ = 42;
  options.server_timeout_us_ = 10 * 1000 * 1000;

  tc::InferResult* result;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, {input0_ptr.get(), input1_ptr.get()}),
      "infer");
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");

  std::string id;
  FAIL_IF_ERR(result_ptr->Id(&id), "response id");
  if (id != "custom-args-1") {
    std::cerr << "error: request id not echoed (got '" << id << "')"
              << std::endl;
    exit(1);
  }
  const uint8_t* buf;
  size_t len;
  FAIL_IF_ERR(result_ptr->RawData("OUTPUT0", &buf, &len), "OUTPUT0 data");
  const int32_t* sums = (const int32_t*)buf;
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != input0_data[i] + input1_data[i]) {
      std::cerr << "error: incorrect sum at " << i << std::endl;
      exit(1);
    }
  }
  std::cout << "custom args OK" << std::endl;
  return 0;
}
