// Drive the `image_ensemble` model (preprocess -> ResNet-50 ensemble
// scheduling): raw uint8 pixels in, top-k classes out (role of reference
// src/c++/examples/ensemble_image_client.cc).

#include <unistd.h>

#include <iostream>
#include <memory>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url;
  std::string protocol = "http";
  size_t topk = 3;
  int opt;
  while ((opt = getopt(argc, argv, "vu:i:c:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      case 'i':
        protocol = optarg;
        break;
      case 'c':
        topk = (size_t)atoi(optarg);
        break;
      default:
        std::cerr << "usage: " << argv[0]
                  << " [-v] [-u url] [-i http|grpc] [-c classes]"
                  << std::endl;
        exit(1);
    }
  }
  for (auto& ch : protocol) {
    ch = tolower(ch);
  }
  if (url.empty()) {
    url = (protocol == "grpc") ? "localhost:8001" : "localhost:8000";
  }

  // deterministic synthetic uint8 image
  std::vector<uint8_t> pixels(224 * 224 * 3);
  uint32_t state = 99;
  for (auto& p : pixels) {
    state = state * 1664525u + 1013904223u;
    p = state >> 24;
  }

  tc::InferInput* input;
  FAIL_IF_ERR(
      tc::InferInput::Create(
          &input, "RAW_IMAGE", {1, 224, 224, 3}, "UINT8"),
      "creating RAW_IMAGE");
  std::shared_ptr<tc::InferInput> input_ptr(input);
  FAIL_IF_ERR(input_ptr->AppendRaw(pixels), "setting RAW_IMAGE data");

  tc::InferRequestedOutput* output;
  FAIL_IF_ERR(
      tc::InferRequestedOutput::Create(&output, "OUTPUT", topk),
      "creating OUTPUT");
  std::shared_ptr<tc::InferRequestedOutput> output_ptr(output);

  tc::InferOptions options("image_ensemble");
  tc::InferResult* result = nullptr;
  if (protocol == "grpc") {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    FAIL_IF_ERR(
        tc::InferenceServerGrpcClient::Create(&client, url, verbose),
        "creating grpc client");
    FAIL_IF_ERR(
        client->Infer(
            &result, options, {input_ptr.get()}, {output_ptr.get()}),
        "infer");
  } else {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    FAIL_IF_ERR(
        tc::InferenceServerHttpClient::Create(&client, url, verbose),
        "creating http client");
    FAIL_IF_ERR(
        client->Infer(
            &result, options, {input_ptr.get()}, {output_ptr.get()}),
        "infer");
  }
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");

  std::vector<std::string> entries;
  FAIL_IF_ERR(
      result_ptr->StringData("OUTPUT", &entries), "parsing class output");
  if (entries.size() != topk) {
    std::cerr << "error: expected " << topk << " classes, got "
              << entries.size() << std::endl;
    exit(1);
  }
  for (const auto& entry : entries) {
    std::cout << "    " << entry << std::endl;
  }
  std::cout << "ensemble image client OK" << std::endl;
  return 0;
}
