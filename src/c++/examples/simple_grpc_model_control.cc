// Explicit model load/unload over gRPC (role of reference
// simple_grpc_model_control.cc).

#include <unistd.h>

#include <iostream>
#include <memory>
#include <vector>

#include "grpc_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

int
main(int argc, char** argv)
{
  bool verbose = false;
  std::string url("localhost:8001");
  int opt;
  while ((opt = getopt(argc, argv, "vu:")) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'u':
        url = optarg;
        break;
      default:
        exit(1);
    }
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(
      tc::InferenceServerGrpcClient::Create(&client, url, verbose),
      "unable to create grpc client");

  FAIL_IF_ERR(client->UnloadModel("simple"), "unloading model");
  bool ready = true;
  FAIL_IF_ERR(client->IsModelReady(&ready, "simple"), "model readiness");
  if (ready) {
    std::cerr << "error: model still ready after unload" << std::endl;
    exit(1);
  }

  // infer must fail while unloaded
  std::vector<int32_t> data(16, 1);
  tc::InferInput* input0;
  tc::InferInput* input1;
  FAIL_IF_ERR(
      tc::InferInput::Create(&input0, "INPUT0", {1, 16}, "INT32"),
      "creating INPUT0");
  FAIL_IF_ERR(
      tc::InferInput::Create(&input1, "INPUT1", {1, 16}, "INT32"),
      "creating INPUT1");
  std::shared_ptr<tc::InferInput> input0_ptr(input0), input1_ptr(input1);
  input0_ptr->AppendRaw(
      (const uint8_t*)data.data(), data.size() * sizeof(int32_t));
  input1_ptr->AppendRaw(
      (const uint8_t*)data.data(), data.size() * sizeof(int32_t));
  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  tc::Error err = client->Infer(
      &result, options, {input0_ptr.get(), input1_ptr.get()});
  if (err.IsOk() && result != nullptr &&
      result->RequestStatus().IsOk()) {
    std::cerr << "error: infer succeeded on unloaded model" << std::endl;
    exit(1);
  }
  delete result;

  FAIL_IF_ERR(client->LoadModel("simple"), "loading model");
  FAIL_IF_ERR(client->IsModelReady(&ready, "simple"), "model readiness");
  if (!ready) {
    std::cerr << "error: model not ready after load" << std::endl;
    exit(1);
  }
  result = nullptr;
  FAIL_IF_ERR(
      client->Infer(
          &result, options, {input0_ptr.get(), input1_ptr.get()}),
      "infer after load");
  std::unique_ptr<tc::InferResult> result_ptr(result);
  FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");

  std::cout << "model control OK" << std::endl;
  return 0;
}
