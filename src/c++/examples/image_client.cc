// Image classification client: PPM/synthetic input, NONE/VGG/INCEPTION
// scaling, batching, sync/async/streaming issue over HTTP or gRPC,
// classification postprocess (role of reference
// src/c++/examples/image_client.cc:64-120; OpenCV replaced by a
// dependency-free PPM reader + nearest-neighbor resample).

#include <getopt.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <fstream>
#include <iostream>
#include <memory>
#include <mutex>
#include <queue>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

#define FAIL_IF_ERR(X, MSG)                              \
  {                                                      \
    tc::Error err = (X);                                 \
    if (!err.IsOk()) {                                   \
      std::cerr << "error: " << (MSG) << ": " << err     \
                << std::endl;                            \
      exit(1);                                           \
    }                                                    \
  }

namespace {

enum class ScaleType { NONE, VGG, INCEPTION };

struct Image {
  std::string name;
  int height = 0;
  int width = 0;
  std::vector<uint8_t> pixels;  // HWC uint8
};

Image
ReadPPM(const std::string& path)
{
  std::ifstream f(path, std::ios::binary);
  if (!f) {
    std::cerr << "error: cannot open " << path << std::endl;
    exit(1);
  }
  std::string magic;
  f >> magic;
  if (magic != "P6") {
    std::cerr << "error: " << path << " is not a binary PPM (P6)"
              << std::endl;
    exit(1);
  }
  int width, height, maxval;
  // skip comments
  auto next_int = [&]() {
    int value;
    while (!(f >> value)) {
      if (f.eof() || f.bad()) {
        std::cerr << "error: truncated or malformed PPM header in "
                  << path << std::endl;
        exit(1);
      }
      f.clear();
      std::string comment;
      std::getline(f, comment);
    }
    return value;
  };
  width = next_int();
  height = next_int();
  maxval = next_int();
  f.get();  // single whitespace after maxval
  if (maxval != 255) {
    std::cerr << "error: only maxval=255 PPM supported" << std::endl;
    exit(1);
  }
  Image img;
  img.name = path;
  img.width = width;
  img.height = height;
  img.pixels.resize((size_t)width * height * 3);
  f.read((char*)img.pixels.data(), img.pixels.size());
  return img;
}

Image
Synthetic(int index)
{
  Image img;
  img.name = "synthetic_" + std::to_string(index);
  img.width = 224;
  img.height = 224;
  img.pixels.resize(224 * 224 * 3);
  uint32_t state = 12345 + index;  // deterministic LCG pixels
  for (auto& p : img.pixels) {
    state = state * 1664525u + 1013904223u;
    p = state >> 24;
  }
  return img;
}

// nearest-neighbor resample to 224x224 + scaling -> FP32 CHW? no: NHWC
std::vector<float>
Preprocess(const Image& img, ScaleType scaling)
{
  constexpr int kSize = 224;
  std::vector<float> out((size_t)kSize * kSize * 3);
  for (int y = 0; y < kSize; ++y) {
    int sy = (int)((int64_t)y * img.height / kSize);
    for (int x = 0; x < kSize; ++x) {
      int sx = (int)((int64_t)x * img.width / kSize);
      const uint8_t* src =
          &img.pixels[((size_t)sy * img.width + sx) * 3];
      float* dst = &out[((size_t)y * kSize + x) * 3];
      for (int c = 0; c < 3; ++c) {
        float v = (float)src[c];
        switch (scaling) {
          case ScaleType::INCEPTION:
            v = v / 127.5f - 1.0f;
            break;
          case ScaleType::VGG: {
            static const float kMean[3] = {123.68f, 116.78f, 103.94f};
            v = v - kMean[c];
            break;
          }
          case ScaleType::NONE:
            break;
        }
        dst[c] = v;
      }
    }
  }
  return out;
}

void
PrintClasses(
    const std::string& image_name, tc::InferResult* result,
    const std::string& output_name, size_t batch_index, size_t classes)
{
  std::vector<std::string> entries;
  FAIL_IF_ERR(
      result->StringData(output_name, &entries), "parsing class output");
  std::cout << "Image '" << image_name << "':" << std::endl;
  for (size_t c = 0; c < classes; ++c) {
    size_t idx = batch_index * classes + c;
    if (idx < entries.size()) {
      std::cout << "    " << entries[idx] << std::endl;
    }
  }
}

}  // namespace

int
main(int argc, char** argv)
{
  bool verbose = false;
  bool async_mode = false;
  bool streaming = false;
  int batch_size = 1;
  size_t topk = 1;
  int synthetic = 0;
  std::string scaling_str = "NONE";
  std::string protocol = "http";
  std::string model_name = "resnet50";
  std::string url;

  static struct option long_opts[] = {
      {"streaming", no_argument, nullptr, 1},
      {"synthetic", required_argument, nullptr, 2},
      {nullptr, 0, nullptr, 0}};
  int opt;
  while ((opt = getopt_long(
              argc, argv, "vab:c:s:i:u:m:", long_opts, nullptr)) != -1) {
    switch (opt) {
      case 'v':
        verbose = true;
        break;
      case 'a':
        async_mode = true;
        break;
      case 'b':
        batch_size = atoi(optarg);
        break;
      case 'c':
        topk = (size_t)atoi(optarg);
        break;
      case 's':
        scaling_str = optarg;
        break;
      case 'i':
        protocol = optarg;
        break;
      case 'u':
        url = optarg;
        break;
      case 'm':
        model_name = optarg;
        break;
      case 1:
        streaming = true;
        break;
      case 2:
        synthetic = atoi(optarg);
        break;
      default:
        std::cerr
            << "usage: " << argv[0]
            << " [-v] [-a] [--streaming] [-b batch] [-c classes]"
            << " [-s NONE|VGG|INCEPTION] [-i http|grpc] [-u url]"
            << " [-m model] [--synthetic N | image.ppm ...]" << std::endl;
        exit(1);
    }
  }
  for (auto& ch : protocol) {
    ch = tolower(ch);
  }
  ScaleType scaling = ScaleType::NONE;
  if (scaling_str == "VGG") {
    scaling = ScaleType::VGG;
  } else if (scaling_str == "INCEPTION") {
    scaling = ScaleType::INCEPTION;
  }
  if (url.empty()) {
    url = (protocol == "grpc") ? "localhost:8001" : "localhost:8000";
  }
  if (streaming && protocol != "grpc") {
    std::cerr << "error: streaming requires -i grpc" << std::endl;
    exit(1);
  }

  std::vector<Image> images;
  if (synthetic > 0) {
    for (int i = 0; i < synthetic; ++i) {
      images.push_back(Synthetic(i));
    }
  } else {
    for (int i = optind; i < argc; ++i) {
      images.push_back(ReadPPM(argv[i]));
    }
  }
  if (images.empty()) {
    std::cerr << "error: no input images (files or --synthetic N)"
              << std::endl;
    exit(1);
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  if (protocol == "grpc") {
    FAIL_IF_ERR(
        tc::InferenceServerGrpcClient::Create(&grpc_client, url, verbose),
        "creating grpc client");
  } else {
    FAIL_IF_ERR(
        tc::InferenceServerHttpClient::Create(&http_client, url, verbose),
        "creating http client");
  }

  // streaming-mode response hand-off (one in-flight request at a time)
  std::mutex stream_mu;
  std::condition_variable stream_cv;
  tc::InferResult* stream_result = nullptr;

  auto infer_batch =
      [&](const std::vector<const Image*>& chunk) -> tc::InferResult* {
    std::vector<float> batch;
    for (const Image* img : chunk) {
      auto pixels = Preprocess(*img, scaling);
      batch.insert(batch.end(), pixels.begin(), pixels.end());
    }
    tc::InferInput* input;
    FAIL_IF_ERR(
        tc::InferInput::Create(
            &input, "INPUT", {(int64_t)chunk.size(), 224, 224, 3},
            "FP32"),
        "creating INPUT");
    std::shared_ptr<tc::InferInput> input_ptr(input);
    FAIL_IF_ERR(
        input_ptr->AppendRaw(
            (const uint8_t*)batch.data(), batch.size() * sizeof(float)),
        "setting INPUT data");
    tc::InferRequestedOutput* output;
    FAIL_IF_ERR(
        tc::InferRequestedOutput::Create(&output, "OUTPUT", topk),
        "creating OUTPUT");
    std::shared_ptr<tc::InferRequestedOutput> output_ptr(output);
    tc::InferOptions options(model_name);

    tc::InferResult* result = nullptr;
    if (streaming) {
      FAIL_IF_ERR(
          grpc_client->AsyncStreamInfer(
              options, {input_ptr.get()}, {output_ptr.get()}),
          "stream infer");
      // stream callback set up by caller fills `result` via capture
      std::unique_lock<std::mutex> lk(stream_mu);
      stream_cv.wait_for(lk, std::chrono::seconds(300), [&] {
        return stream_result != nullptr;
      });
      result = stream_result;
      stream_result = nullptr;
    } else if (async_mode) {
      std::mutex mu;
      std::condition_variable cv;
      tc::InferResult* async_result = nullptr;
      bool done = false;
      auto cb = [&](tc::InferResult* r) {
        std::lock_guard<std::mutex> lk(mu);
        async_result = r;
        done = true;
        cv.notify_all();
      };
      if (protocol == "grpc") {
        FAIL_IF_ERR(
            grpc_client->AsyncInfer(
                cb, options, {input_ptr.get()}, {output_ptr.get()}),
            "async infer");
      } else {
        FAIL_IF_ERR(
            http_client->AsyncInfer(
                cb, options, {input_ptr.get()}, {output_ptr.get()}),
            "async infer");
      }
      std::unique_lock<std::mutex> lk(mu);
      cv.wait_for(lk, std::chrono::seconds(300), [&] { return done; });
      result = async_result;
    } else if (protocol == "grpc") {
      FAIL_IF_ERR(
          grpc_client->Infer(
              &result, options, {input_ptr.get()}, {output_ptr.get()}),
          "infer");
    } else {
      FAIL_IF_ERR(
          http_client->Infer(
              &result, options, {input_ptr.get()}, {output_ptr.get()}),
          "infer");
    }
    return result;
  };

  // streaming shares one callback across requests
  if (streaming) {
    FAIL_IF_ERR(
        grpc_client->StartStream([&](tc::InferResult* r) {
          std::lock_guard<std::mutex> lk(stream_mu);
          stream_result = r;
          stream_cv.notify_all();
        }),
        "starting stream");
  }

  for (size_t start = 0; start < images.size();
       start += (size_t)batch_size) {
    std::vector<const Image*> chunk;
    for (size_t i = start;
         i < images.size() && i < start + (size_t)batch_size; ++i) {
      chunk.push_back(&images[i]);
    }
    tc::InferResult* result = infer_batch(chunk);
    if (result == nullptr) {
      std::cerr << "error: no result" << std::endl;
      exit(1);
    }
    std::unique_ptr<tc::InferResult> result_ptr(result);
    FAIL_IF_ERR(result_ptr->RequestStatus(), "request status");
    for (size_t i = 0; i < chunk.size(); ++i) {
      PrintClasses(chunk[i]->name, result_ptr.get(), "OUTPUT", i, topk);
    }
  }
  if (streaming) {
    grpc_client->StopStream();
  }
  std::cout << "image client OK" << std::endl;
  return 0;
}
