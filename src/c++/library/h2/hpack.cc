#include "hpack.h"

#include <dlfcn.h>

#include <cstring>
#include <mutex>

namespace tc {
namespace h2 {

namespace {

// RFC 7541 Appendix A static table (1-based).
const Header kStaticTable[] = {
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
};
constexpr size_t kStaticTableSize =
    sizeof(kStaticTable) / sizeof(kStaticTable[0]);

// ---------------------------------------------------------------------------
// dlopen'd nghttp2 hd_inflate API (only these five symbols; all operate on
// an opaque inflater pointer plus the simple nghttp2_nv struct, so the ABI
// exposure is minimal and has been stable across libnghttp2.so.14).
//
struct Nghttp2Nv {
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
};

constexpr int kNghttp2InflateFinal = 0x01;
constexpr int kNghttp2InflateEmit = 0x02;

struct Nghttp2Api {
  int (*inflate_new)(void** inflater_ptr) = nullptr;
  long (*inflate_hd2)(
      void* inflater, Nghttp2Nv* nv_out, int* inflate_flags,
      const uint8_t* in, size_t inlen, int in_final) = nullptr;
  int (*inflate_end_headers)(void* inflater) = nullptr;
  void (*inflate_del)(void* inflater) = nullptr;
  bool ok = false;
};

const Nghttp2Api& GetNghttp2()
{
  static Nghttp2Api api;
  static std::once_flag once;
  std::call_once(once, []() {
    void* lib = dlopen("libnghttp2.so.14", RTLD_NOW | RTLD_LOCAL);
    if (lib == nullptr) {
      lib = dlopen("libnghttp2.so", RTLD_NOW | RTLD_LOCAL);
    }
    if (lib == nullptr) {
      return;
    }
    api.inflate_new = reinterpret_cast<int (*)(void**)>(
        dlsym(lib, "nghttp2_hd_inflate_new"));
    api.inflate_hd2 =
        reinterpret_cast<long (*)(void*, Nghttp2Nv*, int*, const uint8_t*,
                                  size_t, int)>(
            dlsym(lib, "nghttp2_hd_inflate_hd2"));
    api.inflate_end_headers = reinterpret_cast<int (*)(void*)>(
        dlsym(lib, "nghttp2_hd_inflate_end_headers"));
    api.inflate_del = reinterpret_cast<void (*)(void*)>(
        dlsym(lib, "nghttp2_hd_inflate_del"));
    api.ok = api.inflate_new != nullptr && api.inflate_hd2 != nullptr &&
             api.inflate_end_headers != nullptr && api.inflate_del != nullptr;
  });
  return api;
}

// ---------------------------------------------------------------------------
// RFC 7541 Appendix B Huffman code: {code, bit length} per symbol 0..255
// plus EOS (index 256).  Used by the fallback decoder so wire compatibility
// does not depend on libnghttp2 or on the peer's encoder choices (gRPC
// C-core Huffman-encodes literals; grpcio-python does not).
//
struct HuffmanCode {
  uint32_t code;
  uint8_t bits;
};

const HuffmanCode kHuffmanCodes[257] = {
    {0x1ff8, 13}, {0x7fffd8, 23}, {0xfffffe2, 28}, {0xfffffe3, 28},
    {0xfffffe4, 28}, {0xfffffe5, 28}, {0xfffffe6, 28}, {0xfffffe7, 28},
    {0xfffffe8, 28}, {0xffffea, 24}, {0x3ffffffc, 30}, {0xfffffe9, 28},
    {0xfffffea, 28}, {0x3ffffffd, 30}, {0xfffffeb, 28}, {0xfffffec, 28},
    {0xfffffed, 28}, {0xfffffee, 28}, {0xfffffef, 28}, {0xffffff0, 28},
    {0xffffff1, 28}, {0xffffff2, 28}, {0x3ffffffe, 30}, {0xffffff3, 28},
    {0xffffff4, 28}, {0xffffff5, 28}, {0xffffff6, 28}, {0xffffff7, 28},
    {0xffffff8, 28}, {0xffffff9, 28}, {0xffffffa, 28}, {0xffffffb, 28},
    {0x14, 6}, {0x3f8, 10}, {0x3f9, 10}, {0xffa, 12},
    {0x1ff9, 13}, {0x15, 6}, {0xf8, 8}, {0x7fa, 11},
    {0x3fa, 10}, {0x3fb, 10}, {0xf9, 8}, {0x7fb, 11},
    {0xfa, 8}, {0x16, 6}, {0x17, 6}, {0x18, 6},
    {0x0, 5}, {0x1, 5}, {0x2, 5}, {0x19, 6},
    {0x1a, 6}, {0x1b, 6}, {0x1c, 6}, {0x1d, 6},
    {0x1e, 6}, {0x1f, 6}, {0x5c, 7}, {0xfb, 8},
    {0x7ffc, 15}, {0x20, 6}, {0xffb, 12}, {0x3fc, 10},
    {0x1ffa, 13}, {0x21, 6}, {0x5d, 7}, {0x5e, 7},
    {0x5f, 7}, {0x60, 7}, {0x61, 7}, {0x62, 7},
    {0x63, 7}, {0x64, 7}, {0x65, 7}, {0x66, 7},
    {0x67, 7}, {0x68, 7}, {0x69, 7}, {0x6a, 7},
    {0x6b, 7}, {0x6c, 7}, {0x6d, 7}, {0x6e, 7},
    {0x6f, 7}, {0x70, 7}, {0x71, 7}, {0x72, 7},
    {0xfc, 8}, {0x73, 7}, {0xfd, 8}, {0x1ffb, 13},
    {0x7fff0, 19}, {0x1ffc, 13}, {0x3ffc, 14}, {0x22, 6},
    {0x7ffd, 15}, {0x3, 5}, {0x23, 6}, {0x4, 5},
    {0x24, 6}, {0x5, 5}, {0x25, 6}, {0x26, 6},
    {0x27, 6}, {0x6, 5}, {0x74, 7}, {0x75, 7},
    {0x28, 6}, {0x29, 6}, {0x2a, 6}, {0x7, 5},
    {0x2b, 6}, {0x76, 7}, {0x2c, 6}, {0x8, 5},
    {0x9, 5}, {0x2d, 6}, {0x77, 7}, {0x78, 7},
    {0x79, 7}, {0x7a, 7}, {0x7b, 7}, {0x7ffe, 15},
    {0x7fc, 11}, {0x3ffd, 14}, {0x1ffd, 13}, {0xffffffc, 28},
    {0xfffe6, 20}, {0x3fffd2, 22}, {0xfffe7, 20}, {0xfffe8, 20},
    {0x3fffd3, 22}, {0x3fffd4, 22}, {0x3fffd5, 22}, {0x7fffd9, 23},
    {0x3fffd6, 22}, {0x7fffda, 23}, {0x7fffdb, 23}, {0x7fffdc, 23},
    {0x7fffdd, 23}, {0x7fffde, 23}, {0xffffeb, 24}, {0x7fffdf, 23},
    {0xffffec, 24}, {0xffffed, 24}, {0x3fffd7, 22}, {0x7fffe0, 23},
    {0xffffee, 24}, {0x7fffe1, 23}, {0x7fffe2, 23}, {0x7fffe3, 23},
    {0x7fffe4, 23}, {0x1fffdc, 21}, {0x3fffd8, 22}, {0x7fffe5, 23},
    {0x3fffd9, 22}, {0x7fffe6, 23}, {0x7fffe7, 23}, {0xffffef, 24},
    {0x3fffda, 22}, {0x1fffdd, 21}, {0xfffe9, 20}, {0x3fffdb, 22},
    {0x3fffdc, 22}, {0x7fffe8, 23}, {0x7fffe9, 23}, {0x1fffde, 21},
    {0x7fffea, 23}, {0x3fffdd, 22}, {0x3fffde, 22}, {0xfffff0, 24},
    {0x1fffdf, 21}, {0x3fffdf, 22}, {0x7fffeb, 23}, {0x7fffec, 23},
    {0x1fffe0, 21}, {0x1fffe1, 21}, {0x3fffe0, 22}, {0x1fffe2, 21},
    {0x7fffed, 23}, {0x3fffe1, 22}, {0x7fffee, 23}, {0x7fffef, 23},
    {0xfffea, 20}, {0x3fffe2, 22}, {0x3fffe3, 22}, {0x3fffe4, 22},
    {0x7ffff0, 23}, {0x3fffe5, 22}, {0x3fffe6, 22}, {0x7ffff1, 23},
    {0x3ffffe0, 26}, {0x3ffffe1, 26}, {0xfffeb, 20}, {0x7fff1, 19},
    {0x3fffe7, 22}, {0x7ffff2, 23}, {0x3fffe8, 22}, {0x1ffffec, 25},
    {0x3ffffe2, 26}, {0x3ffffe3, 26}, {0x3ffffe4, 26}, {0x7ffffde, 27},
    {0x7ffffdf, 27}, {0x3ffffe5, 26}, {0xfffff1, 24}, {0x1ffffed, 25},
    {0x7fff2, 19}, {0x1fffe3, 21}, {0x3ffffe6, 26}, {0x7ffffe0, 27},
    {0x7ffffe1, 27}, {0x3ffffe7, 26}, {0x7ffffe2, 27}, {0xfffff2, 24},
    {0x1fffe4, 21}, {0x1fffe5, 21}, {0x3ffffe8, 26}, {0x3ffffe9, 26},
    {0xffffffd, 28}, {0x7ffffe3, 27}, {0x7ffffe4, 27}, {0x7ffffe5, 27},
    {0xfffec, 20}, {0xfffff3, 24}, {0xfffed, 20}, {0x1fffe6, 21},
    {0x3fffe9, 22}, {0x1fffe7, 21}, {0x1fffe8, 21}, {0x7ffff3, 23},
    {0x3fffea, 22}, {0x3fffeb, 22}, {0x1ffffee, 25}, {0x1ffffef, 25},
    {0xfffff4, 24}, {0xfffff5, 24}, {0x3ffffea, 26}, {0x7ffff4, 23},
    {0x3ffffeb, 26}, {0x7ffffe6, 27}, {0x3ffffec, 26}, {0x3ffffed, 26},
    {0x7ffffe7, 27}, {0x7ffffe8, 27}, {0x7ffffe9, 27}, {0x7ffffea, 27},
    {0x7ffffeb, 27}, {0xffffffe, 28}, {0x7ffffec, 27}, {0x7ffffed, 27},
    {0x7ffffee, 27}, {0x7ffffef, 27}, {0x7fffff0, 27}, {0x3ffffee, 26},
    {0x3fffffff, 30}};

// Bit-trie for decoding, built once.  ~500 nodes; leaves carry symbols.
struct HuffNode {
  int16_t sym = -1;  // >= 0: leaf (256 = EOS)
  int32_t child[2] = {-1, -1};
};

const std::vector<HuffNode>&
HuffTree()
{
  static std::vector<HuffNode> tree;
  static std::once_flag once;
  std::call_once(once, []() {
    tree.emplace_back();  // root
    for (int sym = 0; sym < 257; ++sym) {
      const uint32_t code = kHuffmanCodes[sym].code;
      const int bits = kHuffmanCodes[sym].bits;
      size_t at = 0;
      for (int b = bits - 1; b >= 0; --b) {
        const int bit = (code >> b) & 1;
        if (tree[at].child[bit] < 0) {
          tree[at].child[bit] = static_cast<int32_t>(tree.size());
          tree.emplace_back();
        }
        at = static_cast<size_t>(tree[at].child[bit]);
      }
      tree[at].sym = static_cast<int16_t>(sym);
    }
  });
  return tree;
}

}  // namespace

bool
HuffmanDecode(const uint8_t* data, size_t len, std::string* out)
{
  const auto& tree = HuffTree();
  size_t at = 0;
  int pending_bits = 0;    // bits consumed since the last emitted symbol
  bool pending_ones = true;
  for (size_t i = 0; i < len; ++i) {
    for (int b = 7; b >= 0; --b) {
      const int bit = (data[i] >> b) & 1;
      const int32_t next = tree[at].child[bit];
      if (next < 0) {
        return false;  // not a code prefix
      }
      at = static_cast<size_t>(next);
      ++pending_bits;
      pending_ones = pending_ones && (bit == 1);
      if (tree[at].sym >= 0) {
        if (tree[at].sym == 256) {
          return false;  // explicit EOS in the body is a coding error
        }
        out->push_back(static_cast<char>(tree[at].sym));
        at = 0;
        pending_bits = 0;
        pending_ones = true;
      }
    }
  }
  // Trailing bits must be EOS-prefix padding: all ones, shorter than a byte.
  return pending_bits < 8 && pending_ones;
}

// ---------------------------------------------------------------------------
// integers

void
EncodeInteger(
    uint64_t value, int prefix_bits, uint8_t first_byte_flags,
    std::vector<uint8_t>* out)
{
  const uint64_t max_prefix = (1ull << prefix_bits) - 1;
  if (value < max_prefix) {
    out->push_back(first_byte_flags | static_cast<uint8_t>(value));
    return;
  }
  out->push_back(first_byte_flags | static_cast<uint8_t>(max_prefix));
  value -= max_prefix;
  while (value >= 128) {
    out->push_back(static_cast<uint8_t>((value & 0x7f) | 0x80));
    value >>= 7;
  }
  out->push_back(static_cast<uint8_t>(value));
}

bool
DecodeInteger(
    const uint8_t* data, size_t len, size_t* pos, int prefix_bits,
    uint64_t* value)
{
  if (*pos >= len) {
    return false;
  }
  const uint64_t max_prefix = (1ull << prefix_bits) - 1;
  uint64_t v = data[(*pos)++] & max_prefix;
  if (v < max_prefix) {
    *value = v;
    return true;
  }
  int shift = 0;
  for (;;) {
    if (*pos >= len || shift > 56) {
      return false;
    }
    uint8_t b = data[(*pos)++];
    v += static_cast<uint64_t>(b & 0x7f) << shift;
    shift += 7;
    if ((b & 0x80) == 0) {
      break;
    }
  }
  *value = v;
  return true;
}

// ---------------------------------------------------------------------------
// encoder

namespace {

void
EncodeRawString(const std::string& s, std::vector<uint8_t>* out)
{
  // length with 7-bit prefix, H bit clear (no Huffman)
  EncodeInteger(s.size(), 7, 0x00, out);
  out->insert(out->end(), s.begin(), s.end());
}

}  // namespace

void
HpackEncoder::EncodeBlock(
    const std::vector<Header>& headers, std::vector<uint8_t>* out) const
{
  for (const auto& h : headers) {
    size_t name_index = 0;
    size_t exact_index = 0;
    for (size_t i = 0; i < kStaticTableSize; ++i) {
      if (kStaticTable[i].name == h.name) {
        if (name_index == 0) {
          name_index = i + 1;
        }
        if (kStaticTable[i].value == h.value) {
          exact_index = i + 1;
          break;
        }
      }
    }
    if (exact_index != 0) {
      // indexed header field: 1xxxxxxx
      EncodeInteger(exact_index, 7, 0x80, out);
    } else if (name_index != 0) {
      // literal without indexing, indexed name: 0000xxxx
      EncodeInteger(name_index, 4, 0x00, out);
      EncodeRawString(h.value, out);
    } else {
      // literal without indexing, new name
      out->push_back(0x00);
      EncodeRawString(h.name, out);
      EncodeRawString(h.value, out);
    }
  }
}

// ---------------------------------------------------------------------------
// decoder

HpackDecoder::HpackDecoder(bool use_nghttp2)
{
  const auto& api = GetNghttp2();
  if (use_nghttp2 && api.ok) {
    void* inflater = nullptr;
    if (api.inflate_new(&inflater) == 0) {
      inflater_ = inflater;
    }
  }
}

HpackDecoder::~HpackDecoder()
{
  if (inflater_ != nullptr) {
    GetNghttp2().inflate_del(inflater_);
  }
}

Error
HpackDecoder::DecodeBlock(
    const uint8_t* data, size_t len, std::vector<Header>* out)
{
  if (inflater_ == nullptr) {
    return DecodeBlockFallback(data, len, out);
  }
  const auto& api = GetNghttp2();
  const uint8_t* pos = data;
  size_t remaining = len;
  for (;;) {
    Nghttp2Nv nv;
    int flags = 0;
    long rv = api.inflate_hd2(inflater_, &nv, &flags, pos, remaining, 1);
    if (rv < 0) {
      return Error(
          "HPACK decode failed (nghttp2 rc " + std::to_string(rv) + ")");
    }
    pos += rv;
    remaining -= static_cast<size_t>(rv);
    if (flags & kNghttp2InflateEmit) {
      out->push_back(
          Header{std::string(reinterpret_cast<char*>(nv.name), nv.namelen),
                 std::string(reinterpret_cast<char*>(nv.value), nv.valuelen)});
    }
    if (flags & kNghttp2InflateFinal) {
      api.inflate_end_headers(inflater_);
      return Error::Success;
    }
    if (remaining == 0 && (flags & kNghttp2InflateEmit) == 0) {
      return Error("HPACK decode stalled before end of block");
    }
  }
}

const Header*
HpackDecoder::TableLookup(uint64_t index)
{
  if (index == 0) {
    return nullptr;
  }
  if (index <= kStaticTableSize) {
    return &kStaticTable[index - 1];
  }
  size_t dyn_index = index - kStaticTableSize - 1;
  if (dyn_index >= dyn_.size()) {
    return nullptr;
  }
  return &dyn_[dyn_index];
}

void
HpackDecoder::DynInsert(const Header& h)
{
  const size_t entry_bytes = h.name.size() + h.value.size() + 32;
  dyn_.push_front(h);
  dyn_bytes_ += entry_bytes;
  while (dyn_bytes_ > dyn_max_ && !dyn_.empty()) {
    const Header& old = dyn_.back();
    dyn_bytes_ -= old.name.size() + old.value.size() + 32;
    dyn_.pop_back();
  }
  if (dyn_.empty()) {
    dyn_bytes_ = 0;
  }
}

Error
HpackDecoder::ReadString(
    const uint8_t* data, size_t len, size_t* pos, std::string* out)
{
  if (*pos >= len) {
    return Error("HPACK string truncated");
  }
  const bool huffman = (data[*pos] & 0x80) != 0;
  uint64_t slen = 0;
  if (!DecodeInteger(data, len, pos, 7, &slen)) {
    return Error("HPACK string length truncated");
  }
  if (*pos + slen > len) {
    return Error("HPACK string body truncated");
  }
  if (huffman) {
    out->clear();
    if (!HuffmanDecode(data + *pos, slen, out)) {
      return Error("malformed Huffman-coded HPACK string");
    }
    *pos += slen;
    return Error::Success;
  }
  out->assign(reinterpret_cast<const char*>(data + *pos), slen);
  *pos += slen;
  return Error::Success;
}

Error
HpackDecoder::DecodeBlockFallback(
    const uint8_t* data, size_t len, std::vector<Header>* out)
{
  size_t pos = 0;
  while (pos < len) {
    const uint8_t b = data[pos];
    if (b & 0x80) {
      // indexed header field
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 7, &index)) {
        return Error("HPACK indexed field truncated");
      }
      const Header* h = TableLookup(index);
      if (h == nullptr) {
        return Error("HPACK index " + std::to_string(index) + " out of range");
      }
      out->push_back(*h);
    } else if (b & 0x40) {
      // literal with incremental indexing (6-bit name index)
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 6, &index)) {
        return Error("HPACK literal truncated");
      }
      Header h;
      if (index != 0) {
        const Header* t = TableLookup(index);
        if (t == nullptr) {
          return Error("HPACK name index out of range");
        }
        h.name = t->name;
      } else {
        Error err = ReadString(data, len, &pos, &h.name);
        if (!err.IsOk()) {
          return err;
        }
      }
      Error err = ReadString(data, len, &pos, &h.value);
      if (!err.IsOk()) {
        return err;
      }
      DynInsert(h);
      out->push_back(h);
    } else if (b & 0x20) {
      // dynamic table size update
      uint64_t size = 0;
      if (!DecodeInteger(data, len, &pos, 5, &size)) {
        return Error("HPACK table-size update truncated");
      }
      dyn_max_ = size;
      while (dyn_bytes_ > dyn_max_ && !dyn_.empty()) {
        const Header& old = dyn_.back();
        dyn_bytes_ -= old.name.size() + old.value.size() + 32;
        dyn_.pop_back();
      }
    } else {
      // literal without indexing / never indexed (4-bit name index)
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 4, &index)) {
        return Error("HPACK literal truncated");
      }
      Header h;
      if (index != 0) {
        const Header* t = TableLookup(index);
        if (t == nullptr) {
          return Error("HPACK name index out of range");
        }
        h.name = t->name;
      } else {
        Error err = ReadString(data, len, &pos, &h.name);
        if (!err.IsOk()) {
          return err;
        }
      }
      Error err = ReadString(data, len, &pos, &h.value);
      if (!err.IsOk()) {
        return err;
      }
      out->push_back(h);
    }
  }
  return Error::Success;
}

}  // namespace h2
}  // namespace tc
