#include "hpack.h"

#include <dlfcn.h>

#include <cstring>
#include <mutex>

namespace tc {
namespace h2 {

namespace {

// RFC 7541 Appendix A static table (1-based).
const Header kStaticTable[] = {
    {":authority", ""},
    {":method", "GET"},
    {":method", "POST"},
    {":path", "/"},
    {":path", "/index.html"},
    {":scheme", "http"},
    {":scheme", "https"},
    {":status", "200"},
    {":status", "204"},
    {":status", "206"},
    {":status", "304"},
    {":status", "400"},
    {":status", "404"},
    {":status", "500"},
    {"accept-charset", ""},
    {"accept-encoding", "gzip, deflate"},
    {"accept-language", ""},
    {"accept-ranges", ""},
    {"accept", ""},
    {"access-control-allow-origin", ""},
    {"age", ""},
    {"allow", ""},
    {"authorization", ""},
    {"cache-control", ""},
    {"content-disposition", ""},
    {"content-encoding", ""},
    {"content-language", ""},
    {"content-length", ""},
    {"content-location", ""},
    {"content-range", ""},
    {"content-type", ""},
    {"cookie", ""},
    {"date", ""},
    {"etag", ""},
    {"expect", ""},
    {"expires", ""},
    {"from", ""},
    {"host", ""},
    {"if-match", ""},
    {"if-modified-since", ""},
    {"if-none-match", ""},
    {"if-range", ""},
    {"if-unmodified-since", ""},
    {"last-modified", ""},
    {"link", ""},
    {"location", ""},
    {"max-forwards", ""},
    {"proxy-authenticate", ""},
    {"proxy-authorization", ""},
    {"range", ""},
    {"referer", ""},
    {"refresh", ""},
    {"retry-after", ""},
    {"server", ""},
    {"set-cookie", ""},
    {"strict-transport-security", ""},
    {"transfer-encoding", ""},
    {"user-agent", ""},
    {"vary", ""},
    {"via", ""},
    {"www-authenticate", ""},
};
constexpr size_t kStaticTableSize =
    sizeof(kStaticTable) / sizeof(kStaticTable[0]);

// ---------------------------------------------------------------------------
// dlopen'd nghttp2 hd_inflate API (only these five symbols; all operate on
// an opaque inflater pointer plus the simple nghttp2_nv struct, so the ABI
// exposure is minimal and has been stable across libnghttp2.so.14).
//
struct Nghttp2Nv {
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
};

constexpr int kNghttp2InflateFinal = 0x01;
constexpr int kNghttp2InflateEmit = 0x02;

struct Nghttp2Api {
  int (*inflate_new)(void** inflater_ptr) = nullptr;
  long (*inflate_hd2)(
      void* inflater, Nghttp2Nv* nv_out, int* inflate_flags,
      const uint8_t* in, size_t inlen, int in_final) = nullptr;
  int (*inflate_end_headers)(void* inflater) = nullptr;
  void (*inflate_del)(void* inflater) = nullptr;
  bool ok = false;
};

const Nghttp2Api& GetNghttp2()
{
  static Nghttp2Api api;
  static std::once_flag once;
  std::call_once(once, []() {
    void* lib = dlopen("libnghttp2.so.14", RTLD_NOW | RTLD_LOCAL);
    if (lib == nullptr) {
      lib = dlopen("libnghttp2.so", RTLD_NOW | RTLD_LOCAL);
    }
    if (lib == nullptr) {
      return;
    }
    api.inflate_new = reinterpret_cast<int (*)(void**)>(
        dlsym(lib, "nghttp2_hd_inflate_new"));
    api.inflate_hd2 =
        reinterpret_cast<long (*)(void*, Nghttp2Nv*, int*, const uint8_t*,
                                  size_t, int)>(
            dlsym(lib, "nghttp2_hd_inflate_hd2"));
    api.inflate_end_headers = reinterpret_cast<int (*)(void*)>(
        dlsym(lib, "nghttp2_hd_inflate_end_headers"));
    api.inflate_del = reinterpret_cast<void (*)(void*)>(
        dlsym(lib, "nghttp2_hd_inflate_del"));
    api.ok = api.inflate_new != nullptr && api.inflate_hd2 != nullptr &&
             api.inflate_end_headers != nullptr && api.inflate_del != nullptr;
  });
  return api;
}

}  // namespace

// ---------------------------------------------------------------------------
// integers

void
EncodeInteger(
    uint64_t value, int prefix_bits, uint8_t first_byte_flags,
    std::vector<uint8_t>* out)
{
  const uint64_t max_prefix = (1ull << prefix_bits) - 1;
  if (value < max_prefix) {
    out->push_back(first_byte_flags | static_cast<uint8_t>(value));
    return;
  }
  out->push_back(first_byte_flags | static_cast<uint8_t>(max_prefix));
  value -= max_prefix;
  while (value >= 128) {
    out->push_back(static_cast<uint8_t>((value & 0x7f) | 0x80));
    value >>= 7;
  }
  out->push_back(static_cast<uint8_t>(value));
}

bool
DecodeInteger(
    const uint8_t* data, size_t len, size_t* pos, int prefix_bits,
    uint64_t* value)
{
  if (*pos >= len) {
    return false;
  }
  const uint64_t max_prefix = (1ull << prefix_bits) - 1;
  uint64_t v = data[(*pos)++] & max_prefix;
  if (v < max_prefix) {
    *value = v;
    return true;
  }
  int shift = 0;
  for (;;) {
    if (*pos >= len || shift > 56) {
      return false;
    }
    uint8_t b = data[(*pos)++];
    v += static_cast<uint64_t>(b & 0x7f) << shift;
    shift += 7;
    if ((b & 0x80) == 0) {
      break;
    }
  }
  *value = v;
  return true;
}

// ---------------------------------------------------------------------------
// encoder

namespace {

void
EncodeRawString(const std::string& s, std::vector<uint8_t>* out)
{
  // length with 7-bit prefix, H bit clear (no Huffman)
  EncodeInteger(s.size(), 7, 0x00, out);
  out->insert(out->end(), s.begin(), s.end());
}

}  // namespace

void
HpackEncoder::EncodeBlock(
    const std::vector<Header>& headers, std::vector<uint8_t>* out) const
{
  for (const auto& h : headers) {
    size_t name_index = 0;
    size_t exact_index = 0;
    for (size_t i = 0; i < kStaticTableSize; ++i) {
      if (kStaticTable[i].name == h.name) {
        if (name_index == 0) {
          name_index = i + 1;
        }
        if (kStaticTable[i].value == h.value) {
          exact_index = i + 1;
          break;
        }
      }
    }
    if (exact_index != 0) {
      // indexed header field: 1xxxxxxx
      EncodeInteger(exact_index, 7, 0x80, out);
    } else if (name_index != 0) {
      // literal without indexing, indexed name: 0000xxxx
      EncodeInteger(name_index, 4, 0x00, out);
      EncodeRawString(h.value, out);
    } else {
      // literal without indexing, new name
      out->push_back(0x00);
      EncodeRawString(h.name, out);
      EncodeRawString(h.value, out);
    }
  }
}

// ---------------------------------------------------------------------------
// decoder

HpackDecoder::HpackDecoder(bool use_nghttp2)
{
  const auto& api = GetNghttp2();
  if (use_nghttp2 && api.ok) {
    void* inflater = nullptr;
    if (api.inflate_new(&inflater) == 0) {
      inflater_ = inflater;
    }
  }
}

HpackDecoder::~HpackDecoder()
{
  if (inflater_ != nullptr) {
    GetNghttp2().inflate_del(inflater_);
  }
}

Error
HpackDecoder::DecodeBlock(
    const uint8_t* data, size_t len, std::vector<Header>* out)
{
  if (inflater_ == nullptr) {
    return DecodeBlockFallback(data, len, out);
  }
  const auto& api = GetNghttp2();
  const uint8_t* pos = data;
  size_t remaining = len;
  for (;;) {
    Nghttp2Nv nv;
    int flags = 0;
    long rv = api.inflate_hd2(inflater_, &nv, &flags, pos, remaining, 1);
    if (rv < 0) {
      return Error(
          "HPACK decode failed (nghttp2 rc " + std::to_string(rv) + ")");
    }
    pos += rv;
    remaining -= static_cast<size_t>(rv);
    if (flags & kNghttp2InflateEmit) {
      out->push_back(
          Header{std::string(reinterpret_cast<char*>(nv.name), nv.namelen),
                 std::string(reinterpret_cast<char*>(nv.value), nv.valuelen)});
    }
    if (flags & kNghttp2InflateFinal) {
      api.inflate_end_headers(inflater_);
      return Error::Success;
    }
    if (remaining == 0 && (flags & kNghttp2InflateEmit) == 0) {
      return Error("HPACK decode stalled before end of block");
    }
  }
}

const Header*
HpackDecoder::TableLookup(uint64_t index)
{
  if (index == 0) {
    return nullptr;
  }
  if (index <= kStaticTableSize) {
    return &kStaticTable[index - 1];
  }
  size_t dyn_index = index - kStaticTableSize - 1;
  if (dyn_index >= dyn_.size()) {
    return nullptr;
  }
  return &dyn_[dyn_index];
}

void
HpackDecoder::DynInsert(const Header& h)
{
  const size_t entry_bytes = h.name.size() + h.value.size() + 32;
  dyn_.push_front(h);
  dyn_bytes_ += entry_bytes;
  while (dyn_bytes_ > dyn_max_ && !dyn_.empty()) {
    const Header& old = dyn_.back();
    dyn_bytes_ -= old.name.size() + old.value.size() + 32;
    dyn_.pop_back();
  }
  if (dyn_.empty()) {
    dyn_bytes_ = 0;
  }
}

Error
HpackDecoder::ReadString(
    const uint8_t* data, size_t len, size_t* pos, std::string* out)
{
  if (*pos >= len) {
    return Error("HPACK string truncated");
  }
  const bool huffman = (data[*pos] & 0x80) != 0;
  uint64_t slen = 0;
  if (!DecodeInteger(data, len, pos, 7, &slen)) {
    return Error("HPACK string length truncated");
  }
  if (*pos + slen > len) {
    return Error("HPACK string body truncated");
  }
  if (huffman) {
    return Error(
        "peer sent a Huffman-coded header literal and libnghttp2 is not "
        "available to decode it");
  }
  out->assign(reinterpret_cast<const char*>(data + *pos), slen);
  *pos += slen;
  return Error::Success;
}

Error
HpackDecoder::DecodeBlockFallback(
    const uint8_t* data, size_t len, std::vector<Header>* out)
{
  size_t pos = 0;
  while (pos < len) {
    const uint8_t b = data[pos];
    if (b & 0x80) {
      // indexed header field
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 7, &index)) {
        return Error("HPACK indexed field truncated");
      }
      const Header* h = TableLookup(index);
      if (h == nullptr) {
        return Error("HPACK index " + std::to_string(index) + " out of range");
      }
      out->push_back(*h);
    } else if (b & 0x40) {
      // literal with incremental indexing (6-bit name index)
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 6, &index)) {
        return Error("HPACK literal truncated");
      }
      Header h;
      if (index != 0) {
        const Header* t = TableLookup(index);
        if (t == nullptr) {
          return Error("HPACK name index out of range");
        }
        h.name = t->name;
      } else {
        Error err = ReadString(data, len, &pos, &h.name);
        if (!err.IsOk()) {
          return err;
        }
      }
      Error err = ReadString(data, len, &pos, &h.value);
      if (!err.IsOk()) {
        return err;
      }
      DynInsert(h);
      out->push_back(h);
    } else if (b & 0x20) {
      // dynamic table size update
      uint64_t size = 0;
      if (!DecodeInteger(data, len, &pos, 5, &size)) {
        return Error("HPACK table-size update truncated");
      }
      dyn_max_ = size;
      while (dyn_bytes_ > dyn_max_ && !dyn_.empty()) {
        const Header& old = dyn_.back();
        dyn_bytes_ -= old.name.size() + old.value.size() + 32;
        dyn_.pop_back();
      }
    } else {
      // literal without indexing / never indexed (4-bit name index)
      uint64_t index = 0;
      if (!DecodeInteger(data, len, &pos, 4, &index)) {
        return Error("HPACK literal truncated");
      }
      Header h;
      if (index != 0) {
        const Header* t = TableLookup(index);
        if (t == nullptr) {
          return Error("HPACK name index out of range");
        }
        h.name = t->name;
      } else {
        Error err = ReadString(data, len, &pos, &h.name);
        if (!err.IsOk()) {
          return err;
        }
      }
      Error err = ReadString(data, len, &pos, &h.value);
      if (!err.IsOk()) {
        return err;
      }
      out->push_back(h);
    }
  }
  return Error::Success;
}

}  // namespace h2
}  // namespace tc
