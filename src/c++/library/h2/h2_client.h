// Self-contained client-side HTTP/2 (h2c, RFC 9113) connection.
//
// The reference gRPC client delegates transport to grpc++'s channel
// (reference src/c++/library/grpc_client.cc:78-145); this image has no
// grpc++/nghttp2 headers, so the TPU-native stack speaks HTTP/2 directly
// over a POSIX socket: connection preface + SETTINGS exchange, HPACK
// header blocks (h2/hpack.h), multiplexed streams, both-direction flow
// control, PING/GOAWAY handling, and a reader thread that dispatches
// frames to per-stream handlers.  This is the substrate for the gRPC
// channel (grpc_channel.h) — unary and bidirectional-streaming calls are
// both just h2 streams.

#pragma once

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "hpack.h"
#include "tls.h"

namespace tc {
namespace h2 {

// Per-stream event callbacks, invoked on the connection reader thread.
// Handlers must not issue blocking calls on the same connection.
struct StreamHandler {
  std::function<void(std::vector<Header>&&)> on_headers;
  std::function<void(const uint8_t*, size_t)> on_data;
  std::function<void(std::vector<Header>&&)> on_trailers;
  // Terminal: stream fully closed (ok) or failed (error / RST / GOAWAY).
  std::function<void(Error)> on_close;
};

class H2Connection {
 public:
  // tls.enabled upgrades the connection to h2-over-TLS (ALPN "h2",
  // full-duplex engine — tls.h TlsDuplex); cleartext h2c otherwise.
  static Error Connect(
      std::shared_ptr<H2Connection>* connection, const std::string& host,
      int port, bool verbose = false,
      const TlsOptions& tls = TlsOptions());

  ~H2Connection();
  H2Connection(const H2Connection&) = delete;
  H2Connection& operator=(const H2Connection&) = delete;

  // Open a stream: send HEADERS (END_STREAM when no body follows).
  Error StartStream(
      int32_t* stream_id, const std::vector<Header>& headers,
      StreamHandler handler, bool end_stream);

  // Send body bytes on an open stream; blocks while the peer's flow-
  // control window is exhausted. end_stream half-closes our side.
  Error SendData(
      int32_t stream_id, const uint8_t* data, size_t len, bool end_stream);

  // Abort a stream (RST_STREAM CANCEL). The stream's on_close fires once.
  Error CancelStream(int32_t stream_id);

  // Liveness probe: h2 PING round-trip within timeout_ms.
  Error Ping(int64_t timeout_ms);

  bool Alive() const { return !dead_.load(); }
  const std::string& Authority() const { return authority_; }

  // Graceful shutdown: GOAWAY + close socket + join reader.
  void Shutdown();

 private:
  H2Connection(
      int fd, const std::string& authority, bool verbose,
      std::unique_ptr<TlsDuplex> tls);

  struct Stream {
    StreamHandler handler;
    bool saw_headers = false;       // response HEADERS delivered
    bool remote_closed = false;     // peer sent END_STREAM
    int64_t send_window = 0;
    // CONTINUATION reassembly
    std::vector<uint8_t> header_block;
    bool header_block_end_stream = false;
  };

  void ReaderLoop();
  Error SendFrame(
      uint8_t type, uint8_t flags, int32_t stream_id, const uint8_t* payload,
      size_t len);
  // caller holds write_mu_ (or is single-threaded during setup/teardown)
  Error SendFrameRaw(
      uint8_t type, uint8_t flags, int32_t stream_id, const uint8_t* payload,
      size_t len);
  Error ReadExact(uint8_t* buf, size_t len);
  void HandleSettings(const uint8_t* p, size_t len, uint8_t flags);
  void HandleWindowUpdate(int32_t stream_id, const uint8_t* p, size_t len);
  void HandleHeadersPayload(
      int32_t stream_id, std::vector<uint8_t>&& block, bool end_stream);
  void DeliverHeaderBlock(int32_t stream_id);
  void CloseStream(int32_t stream_id, const Error& err);
  void FailAll(const Error& err);

  int fd_;
  std::string authority_;
  bool verbose_;
  std::unique_ptr<TlsDuplex> tls_;  // null for cleartext h2c
  std::atomic<bool> dead_{false};
  std::string dead_reason_;

  std::thread reader_;
  HpackEncoder encoder_;
  HpackDecoder decoder_;  // reader thread only
  // Header blocks for streams no longer in streams_ (reset/cancelled);
  // reassembled and fed to decoder_ to keep the connection-level HPACK
  // dynamic table in sync.  Guarded by mu_ (CloseStream may move a
  // partial block here from any thread).
  std::map<int32_t, std::vector<uint8_t>> orphan_header_blocks_;

  std::mutex write_mu_;   // socket writes + next_stream_id_
  int32_t next_stream_id_ = 1;

  std::mutex mu_;         // streams_, windows, settings, ping
  std::condition_variable window_cv_;
  std::map<int32_t, Stream> streams_;
  int64_t conn_send_window_ = 65535;
  int64_t peer_initial_window_ = 65535;
  size_t peer_max_frame_size_ = 16384;
  uint64_t ping_counter_ = 0;
  uint64_t last_ping_ack_ = 0;
  std::condition_variable ping_cv_;

  // receive-side flow control replenishment accounting
  int64_t recv_since_update_ = 0;
};

}  // namespace h2
}  // namespace tc
