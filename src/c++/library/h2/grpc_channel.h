// gRPC-over-HTTP/2 channel: RPC call framing on the self-contained h2
// transport (h2_client.h).
//
// Role of the grpc++ channel/completion-queue machinery the reference
// builds on (reference src/c++/library/grpc_client.cc:78-145, 1483-1574):
// unary calls, streaming calls, deadlines (grpc-timeout), grpc-status /
// grpc-message trailer mapping, and connection liveness.  Messages cross
// this API as serialized bytes so the layer stays protobuf-codegen
// agnostic; the typed client (grpc_client.h) parses them.

#pragma once

#include <condition_variable>
#include <cstdint>
#include <functional>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"
#include "h2_client.h"

namespace tc {
namespace h2 {

// One in-flight RPC (one h2 stream). Created via GrpcChannel::StartCall.
class GrpcCall {
 public:
  // Invoked on the connection reader thread per decoded gRPC message.
  using OnMessage = std::function<void(std::string&&)>;
  // Terminal, exactly once: transport error, or grpc-status + message.
  using OnDone =
      std::function<void(Error, int grpc_status, std::string grpc_message)>;

  // Send one length-prefixed gRPC message (serialized protobuf).
  Error Write(const std::string& serialized, bool end_of_calls = false);
  // Half-close our side without a message.
  Error WritesDone();
  Error Cancel();

 private:
  friend class GrpcChannel;
  struct State;
  std::shared_ptr<State> state_;
};

class GrpcChannel {
 public:
  // url is host:port (no scheme) — cleartext h2c, like the reference's
  // insecure channel default; tls.enabled upgrades to h2-over-TLS (the
  // SslCredentials analogue).
  static Error Create(
      std::shared_ptr<GrpcChannel>* channel, const std::string& url,
      bool verbose = false, const TlsOptions& tls = TlsOptions());

  // Start a (possibly streaming) call on /<service>/<method>.
  // timeout_us > 0 adds a grpc-timeout header (server-side deadline).
  Error StartCall(
      GrpcCall* call, const std::string& service, const std::string& method,
      GrpcCall::OnMessage on_message, GrpcCall::OnDone on_done,
      uint64_t timeout_us = 0,
      const std::vector<Header>& extra_headers = {});

  // Blocking unary call. Client-side deadline enforced with stream
  // cancellation when timeout_us > 0.
  Error Unary(
      const std::string& service, const std::string& method,
      const std::string& request, std::string* response,
      uint64_t timeout_us = 0,
      const std::vector<Header>& extra_headers = {});

  bool Alive() const { return conn_ && conn_->Alive(); }
  Error Ping(int64_t timeout_ms) { return conn_->Ping(timeout_ms); }
  // Declare the connection dead: fail all in-flight calls and close the
  // socket (keepalive uses this when a PING ack is missed).
  void Shutdown()
  {
    if (conn_) {
      conn_->Shutdown();
    }
  }

  // the reader thread keeps the connection alive via its own reference;
  // the explicit Shutdown closes the socket so the reader exits and
  // that reference unwinds
  ~GrpcChannel() { Shutdown(); }
  const std::string& Url() const { return url_; }

 private:
  GrpcChannel(const std::string& url) : url_(url) {}

  std::string url_;
  std::shared_ptr<H2Connection> conn_;
};

// Decode gRPC's percent-encoded grpc-message trailer value.
std::string PercentDecode(const std::string& in);

// Encode a grpc-timeout header value: finest unit keeping the number
// within the spec's 8-digit cap (u/m/S/M/H), rounding up.
std::string EncodeGrpcTimeout(uint64_t timeout_us);

}  // namespace h2
}  // namespace tc
