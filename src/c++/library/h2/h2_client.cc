#include "h2_client.h"

#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>

namespace tc {
namespace h2 {

namespace {

constexpr uint8_t kFrameData = 0x0;
constexpr uint8_t kFrameHeaders = 0x1;
constexpr uint8_t kFrameRstStream = 0x3;
constexpr uint8_t kFrameSettings = 0x4;
constexpr uint8_t kFramePushPromise = 0x5;
constexpr uint8_t kFramePing = 0x6;
constexpr uint8_t kFrameGoaway = 0x7;
constexpr uint8_t kFrameWindowUpdate = 0x8;
constexpr uint8_t kFrameContinuation = 0x9;

constexpr uint8_t kFlagEndStream = 0x1;  // DATA/HEADERS
constexpr uint8_t kFlagAck = 0x1;        // SETTINGS/PING
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;

constexpr uint16_t kSettingsInitialWindowSize = 0x4;
constexpr uint16_t kSettingsMaxFrameSize = 0x5;
constexpr uint16_t kSettingsEnablePush = 0x2;

// Our receive windows: per-stream via SETTINGS, connection via an
// immediate WINDOW_UPDATE after the preface.
constexpr int64_t kStreamRecvWindow = 4 << 20;
constexpr int64_t kConnRecvWindowBoost = (32 << 20) - 65535;

const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";

void
PutUint32(uint8_t* p, uint32_t v)
{
  p[0] = (v >> 24) & 0xff;
  p[1] = (v >> 16) & 0xff;
  p[2] = (v >> 8) & 0xff;
  p[3] = v & 0xff;
}

uint32_t
GetUint32(const uint8_t* p)
{
  return (static_cast<uint32_t>(p[0]) << 24) |
         (static_cast<uint32_t>(p[1]) << 16) |
         (static_cast<uint32_t>(p[2]) << 8) | static_cast<uint32_t>(p[3]);
}

}  // namespace

Error
H2Connection::Connect(
    std::shared_ptr<H2Connection>* connection, const std::string& host,
    int port, bool verbose, const TlsOptions& tls)
{
  struct addrinfo hints;
  std::memset(&hints, 0, sizeof(hints));
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_str = std::to_string(port);
  int rc = getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    return Error(
        "failed to resolve " + host + ": " + std::string(gai_strerror(rc)));
  }
  int fd = -1;
  for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) {
      continue;
    }
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
      break;
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    return Error(
        "unable to connect to " + host + ":" + port_str + ": " +
        std::string(strerror(errno)));
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));

  std::unique_ptr<TlsDuplex> tls_session;
  if (tls.enabled) {
    TlsOptions h2_tls = tls;
    if (h2_tls.alpn.empty()) {
      h2_tls.alpn = {"h2"};
    }
    Error tls_err = TlsDuplex::Handshake(&tls_session, fd, h2_tls, host);
    if (!tls_err.IsOk()) {
      close(fd);
      return tls_err;
    }
    if (!tls_session->SelectedAlpn().empty() &&
        tls_session->SelectedAlpn() != "h2") {
      close(fd);
      return Error(
          "TLS peer negotiated ALPN '" + tls_session->SelectedAlpn() +
          "', expected h2");
    }
  }

  auto conn = std::shared_ptr<H2Connection>(new H2Connection(
      fd, host + ":" + port_str, verbose, std::move(tls_session)));

  // preface + SETTINGS(ENABLE_PUSH=0, INITIAL_WINDOW_SIZE) + connection
  // WINDOW_UPDATE, written before the reader starts.
  std::vector<uint8_t> settings;
  auto put_setting = [&settings](uint16_t id, uint32_t value) {
    settings.push_back((id >> 8) & 0xff);
    settings.push_back(id & 0xff);
    size_t at = settings.size();
    settings.resize(at + 4);
    PutUint32(settings.data() + at, value);
  };
  put_setting(kSettingsEnablePush, 0);
  put_setting(kSettingsInitialWindowSize, kStreamRecvWindow);

  if (conn->tls_ != nullptr) {
    Error perr = conn->tls_->SendAll(
        reinterpret_cast<const uint8_t*>(kPreface), sizeof(kPreface) - 1);
    if (!perr.IsOk()) {
      return Error("failed to send h2 preface: " + perr.Message());
    }
  } else if (
      ::send(fd, kPreface, sizeof(kPreface) - 1, MSG_NOSIGNAL) !=
      static_cast<ssize_t>(sizeof(kPreface) - 1)) {
    return Error("failed to send h2 preface: " + std::string(strerror(errno)));
  }
  Error err = conn->SendFrame(
      kFrameSettings, 0, 0, settings.data(), settings.size());
  if (!err.IsOk()) {
    return err;
  }
  uint8_t wu[4];
  PutUint32(wu, kConnRecvWindowBoost);
  err = conn->SendFrame(kFrameWindowUpdate, 0, 0, wu, 4);
  if (!err.IsOk()) {
    return err;
  }

  // the reader holds its own reference for the whole loop: external
  // owners dropping theirs must not destroy the connection while
  // ReaderLoop is mid-frame on this thread (owners call Shutdown() to
  // stop the reader; the self-reference then unwinds cleanly)
  conn->reader_ = std::thread([conn]() { conn->ReaderLoop(); });
  *connection = std::move(conn);
  return Error::Success;
}

H2Connection::H2Connection(
    int fd, const std::string& authority, bool verbose,
    std::unique_ptr<TlsDuplex> tls)
    : fd_(fd), authority_(authority), verbose_(verbose),
      tls_(std::move(tls))
{
}

H2Connection::~H2Connection()
{
  Shutdown();
}

void
H2Connection::Shutdown()
{
  if (!dead_.exchange(true)) {
    dead_reason_ = "connection shut down";
    // best-effort GOAWAY
    uint8_t payload[8] = {0};
    SendFrameRaw(kFrameGoaway, 0, 0, payload, 8);
    if (tls_ != nullptr) {
      tls_->ShutdownNotify();
    }
  }
  ::shutdown(fd_, SHUT_RDWR);
  if (reader_.joinable()) {
    if (std::this_thread::get_id() == reader_.get_id()) {
      reader_.detach();
    } else {
      reader_.join();
    }
  }
  FailAll(Error("connection closed"));
  window_cv_.notify_all();
  ping_cv_.notify_all();
}

Error
H2Connection::SendFrame(
    uint8_t type, uint8_t flags, int32_t stream_id, const uint8_t* payload,
    size_t len)
{
  std::lock_guard<std::mutex> lk(write_mu_);
  return SendFrameRaw(type, flags, stream_id, payload, len);
}

Error
H2Connection::SendFrameRaw(
    uint8_t type, uint8_t flags, int32_t stream_id, const uint8_t* payload,
    size_t len)
{
  uint8_t hdr[9];
  hdr[0] = (len >> 16) & 0xff;
  hdr[1] = (len >> 8) & 0xff;
  hdr[2] = len & 0xff;
  hdr[3] = type;
  hdr[4] = flags;
  PutUint32(hdr + 5, static_cast<uint32_t>(stream_id));
  if (tls_ != nullptr) {
    Error err = tls_->SendAll(hdr, 9);
    if (err.IsOk() && len > 0) {
      err = tls_->SendAll(payload, len);
    }
    if (!err.IsOk()) {
      return Error("h2 send failed: " + err.Message());
    }
    return Error::Success;
  }
  struct iovec iov[2];
  iov[0].iov_base = hdr;
  iov[0].iov_len = 9;
  iov[1].iov_base = const_cast<uint8_t*>(payload);
  iov[1].iov_len = len;
  size_t total = 9 + len;
  size_t sent = 0;
  int iov_at = 0;
  struct msghdr msg;
  while (sent < total) {
    std::memset(&msg, 0, sizeof(msg));
    msg.msg_iov = iov + iov_at;
    msg.msg_iovlen = 2 - iov_at;
    ssize_t n = ::sendmsg(fd_, &msg, MSG_NOSIGNAL);
    if (n <= 0) {
      if (n < 0 && errno == EINTR) {
        continue;
      }
      return Error("h2 send failed: " + std::string(strerror(errno)));
    }
    sent += n;
    // advance iovecs
    size_t adv = n;
    while (adv > 0 && iov_at < 2) {
      if (adv >= iov[iov_at].iov_len) {
        adv -= iov[iov_at].iov_len;
        iov[iov_at].iov_len = 0;
        ++iov_at;
      } else {
        iov[iov_at].iov_base =
            static_cast<uint8_t*>(iov[iov_at].iov_base) + adv;
        iov[iov_at].iov_len -= adv;
        adv = 0;
      }
    }
  }
  return Error::Success;
}

Error
H2Connection::ReadExact(uint8_t* buf, size_t len)
{
  size_t got = 0;
  while (got < len) {
    ssize_t n = tls_ != nullptr
                    ? tls_->Recv(buf + got, len - got)
                    : ::read(fd_, buf + got, len - got);
    if (n == 0) {
      return Error("h2 connection closed by peer");
    }
    if (n < 0) {
      if (errno == EINTR) {
        continue;
      }
      return Error("h2 read failed: " + std::string(strerror(errno)));
    }
    got += n;
  }
  return Error::Success;
}

Error
H2Connection::StartStream(
    int32_t* stream_id, const std::vector<Header>& headers,
    StreamHandler handler, bool end_stream)
{
  if (dead_.load()) {
    return Error("h2 connection is down: " + dead_reason_);
  }
  std::vector<uint8_t> block;
  encoder_.EncodeBlock(headers, &block);

  std::lock_guard<std::mutex> wlk(write_mu_);
  const int32_t id = next_stream_id_;
  next_stream_id_ += 2;
  {
    std::lock_guard<std::mutex> lk(mu_);
    Stream s;
    s.handler = std::move(handler);
    s.send_window = peer_initial_window_;
    streams_.emplace(id, std::move(s));
  }
  size_t max_chunk = peer_max_frame_size_;
  // HEADERS (+ CONTINUATION when the block exceeds one frame)
  size_t off = 0;
  bool first = true;
  do {
    size_t chunk = std::min(block.size() - off, max_chunk);
    uint8_t type = first ? kFrameHeaders : kFrameContinuation;
    uint8_t flags = 0;
    if (first && end_stream) {
      flags |= kFlagEndStream;
    }
    if (off + chunk == block.size()) {
      flags |= kFlagEndHeaders;
    }
    Error err = SendFrameRaw(type, flags, id, block.data() + off, chunk);
    if (!err.IsOk()) {
      std::lock_guard<std::mutex> lk(mu_);
      streams_.erase(id);
      return err;
    }
    off += chunk;
    first = false;
  } while (off < block.size());
  *stream_id = id;
  return Error::Success;
}

Error
H2Connection::SendData(
    int32_t stream_id, const uint8_t* data, size_t len, bool end_stream)
{
  size_t off = 0;
  do {
    size_t chunk = 0;
    {
      std::unique_lock<std::mutex> lk(mu_);
      window_cv_.wait(lk, [&]() {
        if (dead_.load()) {
          return true;
        }
        auto it = streams_.find(stream_id);
        if (it == streams_.end()) {
          return true;  // stream was reset
        }
        if (off >= len) {
          return true;  // zero-length end-of-stream frame needs no window
        }
        return conn_send_window_ > 0 && it->second.send_window > 0;
      });
      if (dead_.load()) {
        return Error("h2 connection is down: " + dead_reason_);
      }
      auto it = streams_.find(stream_id);
      if (it == streams_.end()) {
        return Error("stream closed by peer before request was sent");
      }
      if (len > off) {
        chunk = std::min(
            {len - off, static_cast<size_t>(conn_send_window_),
             static_cast<size_t>(it->second.send_window),
             peer_max_frame_size_});
        conn_send_window_ -= chunk;
        it->second.send_window -= chunk;
      }
    }
    const bool last = (off + chunk >= len);
    uint8_t flags = (last && end_stream) ? kFlagEndStream : 0;
    Error err = SendFrame(kFrameData, flags, stream_id, data + off, chunk);
    if (!err.IsOk()) {
      return err;
    }
    off += chunk;
  } while (off < len);
  return Error::Success;
}

Error
H2Connection::CancelStream(int32_t stream_id)
{
  uint8_t payload[4];
  PutUint32(payload, 0x8);  // CANCEL
  Error err = SendFrame(kFrameRstStream, 0, stream_id, payload, 4);
  CloseStream(stream_id, Error("stream cancelled"));
  return err;
}

Error
H2Connection::Ping(int64_t timeout_ms)
{
  uint64_t my_ping;
  {
    std::lock_guard<std::mutex> lk(mu_);
    my_ping = ++ping_counter_;
  }
  uint8_t payload[8];
  for (int i = 0; i < 8; ++i) {
    payload[i] = (my_ping >> (8 * (7 - i))) & 0xff;
  }
  Error err = SendFrame(kFramePing, 0, 0, payload, 8);
  if (!err.IsOk()) {
    return err;
  }
  std::unique_lock<std::mutex> lk(mu_);
  bool ok = ping_cv_.wait_for(
      lk, std::chrono::milliseconds(timeout_ms),
      [&]() { return dead_.load() || last_ping_ack_ >= my_ping; });
  if (dead_.load()) {
    return Error("h2 connection is down: " + dead_reason_);
  }
  if (!ok) {
    return Error("h2 ping timed out");
  }
  return Error::Success;
}

void
H2Connection::ReaderLoop()
{
  std::vector<uint8_t> payload;
  for (;;) {
    uint8_t hdr[9];
    Error err = ReadExact(hdr, 9);
    if (!err.IsOk()) {
      if (!dead_.exchange(true)) {
        dead_reason_ = err.Message();
      }
      FailAll(Error("h2 connection lost: " + dead_reason_));
      window_cv_.notify_all();
      ping_cv_.notify_all();
      return;
    }
    const size_t len = (static_cast<size_t>(hdr[0]) << 16) |
                       (static_cast<size_t>(hdr[1]) << 8) | hdr[2];
    const uint8_t type = hdr[3];
    const uint8_t flags = hdr[4];
    const int32_t stream_id =
        static_cast<int32_t>(GetUint32(hdr + 5) & 0x7fffffff);
    payload.resize(len);
    if (len > 0) {
      err = ReadExact(payload.data(), len);
      if (!err.IsOk()) {
        if (!dead_.exchange(true)) {
          dead_reason_ = err.Message();
        }
        FailAll(Error("h2 connection lost: " + dead_reason_));
        window_cv_.notify_all();
        ping_cv_.notify_all();
        return;
      }
    }

    switch (type) {
      case kFrameData: {
        const uint8_t* data = payload.data();
        size_t data_len = len;
        if (flags & kFlagPadded) {
          if (data_len < 1) {
            break;
          }
          uint8_t pad = data[0];
          data += 1;
          data_len -= 1;
          data_len = (pad <= data_len) ? data_len - pad : 0;
        }
        StreamHandler handler;
        bool deliver = false;
        bool closed = false;
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto it = streams_.find(stream_id);
          if (it != streams_.end()) {
            deliver = true;
            handler = it->second.handler;
            if (flags & kFlagEndStream) {
              it->second.remote_closed = true;
              closed = true;
            }
          }
        }
        if (deliver && data_len > 0 && handler.on_data) {
          handler.on_data(data, data_len);
        }
        // replenish both windows for the full payload (padding included)
        if (len > 0) {
          uint8_t wu[4];
          PutUint32(wu, static_cast<uint32_t>(len));
          SendFrame(kFrameWindowUpdate, 0, 0, wu, 4);
          if (deliver && !closed) {
            SendFrame(kFrameWindowUpdate, 0, stream_id, wu, 4);
          }
        }
        if (closed) {
          CloseStream(stream_id, Error::Success);
        }
        break;
      }
      case kFrameHeaders: {
        const uint8_t* block = payload.data();
        size_t block_len = len;
        if (flags & kFlagPadded) {
          if (block_len < 1) {
            break;
          }
          uint8_t pad = block[0];
          block += 1;
          block_len -= 1;
          block_len = (pad <= block_len) ? block_len - pad : 0;
        }
        if (flags & kFlagPriority) {
          if (block_len < 5) {
            break;
          }
          block += 5;
          block_len -= 5;
        }
        std::vector<uint8_t> copy(block, block + block_len);
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto it = streams_.find(stream_id);
          if (it != streams_.end()) {
            it->second.header_block = std::move(copy);
            it->second.header_block_end_stream =
                (flags & kFlagEndStream) != 0;
          } else {
            // The HPACK dynamic table is connection-level state: blocks
            // for streams we already closed (e.g. trailers arriving after
            // a CancelStream) still carry table inserts, so they must
            // reach the decoder or every later RPC on this connection
            // decodes garbage.  Buffer them for DeliverHeaderBlock.
            orphan_header_blocks_[stream_id] = std::move(copy);
          }
        }
        if (flags & kFlagEndHeaders) {
          DeliverHeaderBlock(stream_id);
        }
        break;
      }
      case kFrameContinuation: {
        bool complete = (flags & kFlagEndHeaders) != 0;
        {
          std::lock_guard<std::mutex> lk(mu_);
          auto it = streams_.find(stream_id);
          if (it != streams_.end()) {
            it->second.header_block.insert(
                it->second.header_block.end(), payload.begin(), payload.end());
          } else {
            auto& blk = orphan_header_blocks_[stream_id];
            blk.insert(blk.end(), payload.begin(), payload.end());
          }
        }
        if (complete) {
          DeliverHeaderBlock(stream_id);
        }
        break;
      }
      case kFrameSettings:
        HandleSettings(payload.data(), len, flags);
        break;
      case kFramePing: {
        if (len != 8) {
          break;
        }
        if (flags & kFlagAck) {
          uint64_t v = 0;
          for (int i = 0; i < 8; ++i) {
            v = (v << 8) | payload[i];
          }
          std::lock_guard<std::mutex> lk(mu_);
          if (v > last_ping_ack_) {
            last_ping_ack_ = v;
          }
          ping_cv_.notify_all();
        } else {
          SendFrame(kFramePing, kFlagAck, 0, payload.data(), 8);
        }
        break;
      }
      case kFrameWindowUpdate:
        HandleWindowUpdate(stream_id, payload.data(), len);
        break;
      case kFrameRstStream: {
        uint32_t code = (len >= 4) ? GetUint32(payload.data()) : 0;
        CloseStream(
            stream_id,
            Error("stream reset by server (h2 error " + std::to_string(code) +
                  ")"));
        break;
      }
      case kFrameGoaway: {
        uint32_t last_id = (len >= 4) ? (GetUint32(payload.data()) & 0x7fffffff) : 0;
        uint32_t code = (len >= 8) ? GetUint32(payload.data() + 4) : 0;
        std::string debug;
        if (len > 8) {
          debug.assign(
              reinterpret_cast<const char*>(payload.data() + 8), len - 8);
        }
        if (!dead_.exchange(true)) {
          dead_reason_ = "server sent GOAWAY (error " + std::to_string(code) +
                         (debug.empty() ? "" : ", " + debug) + ")";
        }
        // fail streams the server will not process
        std::vector<int32_t> doomed;
        {
          std::lock_guard<std::mutex> lk(mu_);
          for (const auto& kv : streams_) {
            if (static_cast<uint32_t>(kv.first) > last_id || code != 0) {
              doomed.push_back(kv.first);
            }
          }
        }
        for (int32_t id : doomed) {
          CloseStream(id, Error(dead_reason_));
        }
        window_cv_.notify_all();
        ping_cv_.notify_all();
        break;
      }
      case kFramePushPromise:
        // pushes are disabled via SETTINGS; ignore defensively
        break;
      default:
        break;
    }
  }
}

void
H2Connection::HandleSettings(const uint8_t* p, size_t len, uint8_t flags)
{
  if (flags & kFlagAck) {
    return;
  }
  // Apply + ACK atomically w.r.t. other writers (write_mu_ held across
  // both, matching SendHeaders' write_mu_ -> mu_ lock order): peers —
  // grpc-core among them — keep enforcing their previous limits until
  // the ACK arrives, so no frame computed with the NEW values may reach
  // the wire ahead of the ACK.
  std::lock_guard<std::mutex> wlk(write_mu_);
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (size_t off = 0; off + 6 <= len; off += 6) {
      uint16_t id = (static_cast<uint16_t>(p[off]) << 8) | p[off + 1];
      uint32_t value = GetUint32(p + off + 2);
      switch (id) {
        case kSettingsInitialWindowSize: {
          int64_t delta =
              static_cast<int64_t>(value) - peer_initial_window_;
          peer_initial_window_ = value;
          for (auto& kv : streams_) {
            kv.second.send_window += delta;
          }
          break;
        }
        case kSettingsMaxFrameSize:
          if (value >= 16384 && value <= (1u << 24) - 1) {
            peer_max_frame_size_ = value;
          }
          break;
        default:
          break;
      }
    }
  }
  SendFrameRaw(kFrameSettings, kFlagAck, 0, nullptr, 0);
  window_cv_.notify_all();
}

void
H2Connection::HandleWindowUpdate(
    int32_t stream_id, const uint8_t* p, size_t len)
{
  if (len < 4) {
    return;
  }
  uint32_t inc = GetUint32(p) & 0x7fffffff;
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (stream_id == 0) {
      conn_send_window_ += inc;
    } else {
      auto it = streams_.find(stream_id);
      if (it != streams_.end()) {
        it->second.send_window += inc;
      }
    }
  }
  window_cv_.notify_all();
}

void
H2Connection::DeliverHeaderBlock(int32_t stream_id)
{
  std::vector<uint8_t> block;
  bool end_stream = false;
  bool saw_headers_before = false;
  StreamHandler handler;
  bool found = false;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = streams_.find(stream_id);
    if (it != streams_.end()) {
      found = true;
      block = std::move(it->second.header_block);
      it->second.header_block.clear();
      end_stream = it->second.header_block_end_stream;
      saw_headers_before = it->second.saw_headers;
      it->second.saw_headers = true;
      handler = it->second.handler;
      if (end_stream) {
        it->second.remote_closed = true;
      }
    }
  }
  if (!found) {
    // Closed/unknown stream: the block was buffered in
    // orphan_header_blocks_ by the HEADERS/CONTINUATION cases (and/or
    // moved there by CloseStream mid-reassembly).
    std::lock_guard<std::mutex> lk(mu_);
    auto it = orphan_header_blocks_.find(stream_id);
    if (it != orphan_header_blocks_.end()) {
      block = std::move(it->second);
      orphan_header_blocks_.erase(it);
    }
  }
  // The HPACK dynamic table is connection-level state: decode even for
  // unknown streams to keep the decoder in sync.
  std::vector<Header> headers;
  Error err = decoder_.DecodeBlock(block.data(), block.size(), &headers);
  if (!found) {
    return;
  }
  if (!err.IsOk()) {
    CloseStream(stream_id, err);
    return;
  }
  if (!saw_headers_before) {
    if (handler.on_headers) {
      handler.on_headers(std::move(headers));
    }
  } else {
    if (handler.on_trailers) {
      handler.on_trailers(std::move(headers));
    }
  }
  if (end_stream) {
    CloseStream(stream_id, Error::Success);
  }
}

void
H2Connection::CloseStream(int32_t stream_id, const Error& err)
{
  StreamHandler handler;
  bool found = false;
  {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = streams_.find(stream_id);
    if (it != streams_.end()) {
      handler = it->second.handler;
      if (!it->second.header_block.empty()) {
        // mid-reassembly close (e.g. CancelStream between HEADERS and
        // CONTINUATION): keep the partial block so the orphan path can
        // finish reassembly and keep the HPACK table in sync
        orphan_header_blocks_[stream_id] =
            std::move(it->second.header_block);
      }
      streams_.erase(it);
      found = true;
    }
  }
  if (found) {
    window_cv_.notify_all();
    if (handler.on_close) {
      handler.on_close(err);
    }
  }
}

void
H2Connection::FailAll(const Error& err)
{
  std::vector<StreamHandler> handlers;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : streams_) {
      handlers.push_back(kv.second.handler);
    }
    streams_.clear();
  }
  for (auto& h : handlers) {
    if (h.on_close) {
      h.on_close(err);
    }
  }
}

}  // namespace h2
}  // namespace tc
