// HPACK (RFC 7541) header codec for the self-contained HTTP/2 transport.
//
// The reference's gRPC client rides grpc++ and never sees HPACK; this
// image has no grpc++ headers, so the TPU-native stack carries its own
// HTTP/2 layer (h2_client.{h,cc}) and this codec.
//
// Encoder: emits indexed fields for exact static-table matches and
// literal-without-indexing otherwise — never Huffman, never dynamic-table
// inserts.  Both are always legal for a sender and keep the encoder
// state-free (one less thing to corrupt across streams).
//
// Decoder: a conformant peer may use Huffman coding and dynamic-table
// inserts, so decoding needs the full protocol.  When libnghttp2 is
// present (runtime .so only in this image — no headers) its tiny, ABI-
// stable hd_inflate API is dlopen'd for the job; otherwise a self-
// contained fallback decoder handles the full protocol including RFC
// 7541 Appendix B Huffman-coded literals (gRPC C-core Huffman-encodes;
// wire compatibility must not depend on the peer's encoder choices).

#pragma once

#include <cstdint>
#include <deque>
#include <string>
#include <vector>

#include "common.h"

namespace tc {
namespace h2 {

struct Header {
  std::string name;
  std::string value;
};

// Append an HPACK-coded integer with the given prefix size to `out`.
// `first_byte_flags` carries the pattern bits above the prefix.
void EncodeInteger(
    uint64_t value, int prefix_bits, uint8_t first_byte_flags,
    std::vector<uint8_t>* out);

// Decode an HPACK integer; advances *pos. Returns false on truncation.
bool DecodeInteger(
    const uint8_t* data, size_t len, size_t* pos, int prefix_bits,
    uint64_t* value);

// Decode an RFC 7541 Appendix B Huffman-coded string.  Returns false on
// a non-prefix bit sequence, explicit EOS, or invalid padding.
bool HuffmanDecode(const uint8_t* data, size_t len, std::string* out);

class HpackEncoder {
 public:
  // Encode a complete header block (no CONTINUATION splitting here; the
  // frame layer handles that).
  void EncodeBlock(
      const std::vector<Header>& headers, std::vector<uint8_t>* out) const;
};

class HpackDecoder {
 public:
  // use_nghttp2=false forces the self-contained fallback decoder (tests)
  explicit HpackDecoder(bool use_nghttp2 = true);
  ~HpackDecoder();
  HpackDecoder(const HpackDecoder&) = delete;
  HpackDecoder& operator=(const HpackDecoder&) = delete;

  // Decode one complete header block (after CONTINUATION reassembly).
  // The decoder is stateful across blocks on one connection (dynamic
  // table); use one instance per connection, reader thread only.
  Error DecodeBlock(
      const uint8_t* data, size_t len, std::vector<Header>* out);

  // True when the nghttp2 inflater backs this decoder (test hook).
  bool UsingNghttp2() const { return inflater_ != nullptr; }

 private:
  Error DecodeBlockFallback(
      const uint8_t* data, size_t len, std::vector<Header>* out);
  Error ReadString(
      const uint8_t* data, size_t len, size_t* pos, std::string* out);
  const Header* TableLookup(uint64_t index);
  void DynInsert(const Header& h);

  void* inflater_ = nullptr;  // nghttp2_hd_inflater*, when available

  // fallback dynamic table (newest first, per RFC 7541 §2.3.2)
  std::deque<Header> dyn_;
  size_t dyn_bytes_ = 0;
  size_t dyn_max_ = 4096;
};

}  // namespace h2
}  // namespace tc
