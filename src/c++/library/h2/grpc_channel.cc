#include "grpc_channel.h"

#include <zlib.h>

#include <cstring>

namespace tc {
namespace h2 {

namespace {

// gRPC message compression ("gzip" = RFC1952, "deflate" = RFC1950 zlib).
Error
CompressMessage(
    const std::string& algorithm, const std::string& in, std::string* out)
{
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  const int window_bits = algorithm == "gzip" ? 15 + 16 : 15;
  if (deflateInit2(
          &zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window_bits, 8,
          Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("deflateInit2 failed");
  }
  out->resize(deflateBound(&zs, in.size()));
  zs.next_in =
      reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  zs.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  zs.avail_out = static_cast<uInt>(out->size());
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) {
    return Error("deflate failed");
  }
  out->resize(zs.total_out);
  return Error::Success;
}

// Auto-detecting inflate (15+32: zlib or gzip headers).
Error
DecompressMessage(const std::string& in, std::string* out)
{
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, 15 + 32) != Z_OK) {
    return Error("inflateInit2 failed");
  }
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  out->clear();
  char buf[65536];
  int rc = Z_OK;
  do {
    zs.next_out = reinterpret_cast<Bytef*>(buf);
    zs.avail_out = sizeof(buf);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return Error("inflate failed (corrupt compressed gRPC message)");
    }
    out->append(buf, sizeof(buf) - zs.avail_out);
    // mirror the send side's 2 GB gRPC message cap: without it a small
    // gzip bomb from a hostile server inflates unboundedly into client
    // memory
    if (out->size() > static_cast<size_t>(INT32_MAX)) {
      inflateEnd(&zs);
      return Error(
          "decompressed gRPC message exceeds the 2 GB message limit");
    }
  } while (rc != Z_STREAM_END && (zs.avail_in > 0 || zs.avail_out == 0));
  inflateEnd(&zs);
  if (rc != Z_STREAM_END) {
    return Error("truncated compressed gRPC message");
  }
  return Error::Success;
}

Error
ParseHostPort(const std::string& url, std::string* host, int* port)
{
  std::string u = url;
  // tolerate scheme prefixes
  auto scheme = u.find("://");
  if (scheme != std::string::npos) {
    u = u.substr(scheme + 3);
  }
  auto slash = u.find('/');
  if (slash != std::string::npos) {
    u = u.substr(0, slash);
  }
  auto colon = u.rfind(':');
  if (colon == std::string::npos) {
    *host = u;
    *port = 8001;
    return Error::Success;
  }
  *host = u.substr(0, colon);
  try {
    *port = std::stoi(u.substr(colon + 1));
  }
  catch (...) {
    return Error("invalid port in url '" + url + "'");
  }
  return Error::Success;
}

}  // namespace

// gRPC caps the grpc-timeout TimeoutValue at 8 decimal digits; pick the
// finest unit that fits (the reference inherits this scaling from grpc++'s
// set_deadline, reference grpc_client.cc:1031).  Rounds up so the deadline
// is never shortened.
std::string
EncodeGrpcTimeout(uint64_t timeout_us)
{
  struct Unit {
    char suffix;
    uint64_t per_us;
  };
  constexpr uint64_t kMax = 99999999;  // 8 digits
  constexpr Unit kUnits[] = {
      {'u', 1},
      {'m', 1000},
      {'S', 1000000},
      {'M', 60ull * 1000000},
      {'H', 3600ull * 1000000},
  };
  for (const auto& u : kUnits) {
    // ceil-divide without the +(per_us-1) addition: timeout_us near
    // UINT64_MAX must not wrap to a tiny deadline
    uint64_t v = timeout_us / u.per_us + (timeout_us % u.per_us != 0);
    if (v <= kMax) {
      return std::to_string(v) + u.suffix;
    }
  }
  return std::to_string(kMax) + 'H';
}

std::string
PercentDecode(const std::string& in)
{
  std::string out;
  out.reserve(in.size());
  for (size_t i = 0; i < in.size(); ++i) {
    if (in[i] == '%' && i + 2 < in.size() && isxdigit(in[i + 1]) &&
        isxdigit(in[i + 2])) {
      out.push_back(static_cast<char>(
          std::stoi(in.substr(i + 1, 2), nullptr, 16)));
      i += 2;
    } else {
      out.push_back(in[i]);
    }
  }
  return out;
}

//==============================================================================
// GrpcCall

struct GrpcCall::State {
  std::shared_ptr<H2Connection> conn;
  int32_t stream_id = 0;

  // per-message compression for sends (from the call's grpc-encoding
  // header); receives auto-detect whenever the compressed flag is set
  std::string send_encoding;

  // reader-thread state: gRPC message reassembly
  std::string recv_buf;
  GrpcCall::OnMessage on_message;
  GrpcCall::OnDone on_done;

  std::mutex mu;
  bool done = false;
  bool status_seen = false;
  int grpc_status = -1;
  std::string grpc_message;

  void ScanStatus(const std::vector<Header>& headers)
  {
    std::lock_guard<std::mutex> lk(mu);
    for (const auto& h : headers) {
      if (h.name == "grpc-status") {
        status_seen = true;
        try {
          grpc_status = std::stoi(h.value);
        }
        catch (...) {
          grpc_status = 2;  // UNKNOWN
        }
      } else if (h.name == "grpc-message") {
        grpc_message = PercentDecode(h.value);
      }
    }
  }

  // Reader thread: append data, emit complete length-prefixed messages.
  Error ConsumeData(const uint8_t* data, size_t len)
  {
    recv_buf.append(reinterpret_cast<const char*>(data), len);
    size_t off = 0;
    while (recv_buf.size() - off >= 5) {
      const uint8_t* p =
          reinterpret_cast<const uint8_t*>(recv_buf.data()) + off;
      const uint8_t compressed = p[0];
      const uint32_t msg_len = (static_cast<uint32_t>(p[1]) << 24) |
                               (static_cast<uint32_t>(p[2]) << 16) |
                               (static_cast<uint32_t>(p[3]) << 8) | p[4];
      if (recv_buf.size() - off - 5 < msg_len) {
        break;
      }
      if (compressed != 0) {
        std::string plain;
        Error err =
            DecompressMessage(recv_buf.substr(off + 5, msg_len), &plain);
        if (!err.IsOk()) {
          return err;
        }
        if (on_message) {
          on_message(std::move(plain));
        }
      } else if (on_message) {
        on_message(recv_buf.substr(off + 5, msg_len));
      }
      off += 5 + msg_len;
    }
    if (off > 0) {
      recv_buf.erase(0, off);
    }
    return Error::Success;
  }

  void Finish(const Error& transport_err)
  {
    OnDone cb;
    Error err;
    int status;
    std::string message;
    {
      std::lock_guard<std::mutex> lk(mu);
      if (done) {
        return;
      }
      done = true;
      cb = on_done;
      if (!transport_err.IsOk()) {
        err = transport_err;
        status = -1;
      } else if (!status_seen) {
        err = Error("stream closed without grpc-status");
        status = -1;
      } else {
        err = Error::Success;
        status = grpc_status;
      }
      message = grpc_message;
    }
    if (cb) {
      cb(err, status, message);
    }
  }
};

Error
GrpcCall::Write(const std::string& serialized, bool end_of_calls)
{
  if (!state_) {
    return Error("call not started");
  }
  if (serialized.size() > 0x7fffffffull) {
    // role of the reference's 2 GB protobuf guard (grpc_client.cc:1345-1353)
    return Error("gRPC message exceeds 2 GB limit");
  }
  const std::string* payload = &serialized;
  std::string compressed_payload;
  bool compressed = false;
  if (!state_->send_encoding.empty() && !serialized.empty()) {
    Error cerr = CompressMessage(
        state_->send_encoding, serialized, &compressed_payload);
    if (!cerr.IsOk()) {
      return cerr;
    }
    payload = &compressed_payload;
    compressed = true;
  }
  std::string framed;
  framed.reserve(5 + payload->size());
  framed.push_back(compressed ? '\1' : '\0');
  const uint32_t len = static_cast<uint32_t>(payload->size());
  framed.push_back(static_cast<char>((len >> 24) & 0xff));
  framed.push_back(static_cast<char>((len >> 16) & 0xff));
  framed.push_back(static_cast<char>((len >> 8) & 0xff));
  framed.push_back(static_cast<char>(len & 0xff));
  framed += *payload;
  return state_->conn->SendData(
      state_->stream_id, reinterpret_cast<const uint8_t*>(framed.data()),
      framed.size(), end_of_calls);
}

Error
GrpcCall::WritesDone()
{
  if (!state_) {
    return Error("call not started");
  }
  return state_->conn->SendData(state_->stream_id, nullptr, 0, true);
}

Error
GrpcCall::Cancel()
{
  if (!state_) {
    return Error("call not started");
  }
  return state_->conn->CancelStream(state_->stream_id);
}

//==============================================================================
// GrpcChannel

Error
GrpcChannel::Create(
    std::shared_ptr<GrpcChannel>* channel, const std::string& url,
    bool verbose, const TlsOptions& tls)
{
  std::string host;
  int port = 0;
  Error err = ParseHostPort(url, &host, &port);
  if (!err.IsOk()) {
    return err;
  }
  auto ch = std::shared_ptr<GrpcChannel>(new GrpcChannel(url));
  err = H2Connection::Connect(&ch->conn_, host, port, verbose, tls);
  if (!err.IsOk()) {
    return err;
  }
  *channel = std::move(ch);
  return Error::Success;
}

Error
GrpcChannel::StartCall(
    GrpcCall* call, const std::string& service, const std::string& method,
    GrpcCall::OnMessage on_message, GrpcCall::OnDone on_done,
    uint64_t timeout_us, const std::vector<Header>& extra_headers)
{
  auto state = std::make_shared<GrpcCall::State>();
  state->conn = conn_;
  state->on_message = std::move(on_message);
  state->on_done = std::move(on_done);

  std::vector<Header> headers = {
      {":method", "POST"},
      {":scheme", "http"},
      {":path", "/" + service + "/" + method},
      {":authority", conn_->Authority()},
      {"te", "trailers"},
      {"content-type", "application/grpc"},
      {"user-agent", "tpu-triton-client-cc-h2"},
  };
  if (timeout_us > 0) {
    headers.push_back({"grpc-timeout", EncodeGrpcTimeout(timeout_us)});
  }
  // the receive path auto-detects either algorithm on the compressed flag
  headers.push_back({"grpc-accept-encoding", "identity,deflate,gzip"});
  for (const auto& h : extra_headers) {
    headers.push_back(h);
    if (h.name == "grpc-encoding" && h.value != "identity" &&
        h.value != "none") {
      state->send_encoding = h.value;
    }
  }

  StreamHandler handler;
  handler.on_headers = [state](std::vector<Header>&& hs) {
    // trailers-only responses carry grpc-status here
    state->ScanStatus(hs);
  };
  handler.on_data = [state](const uint8_t* data, size_t len) {
    Error err = state->ConsumeData(data, len);
    if (!err.IsOk()) {
      state->conn->CancelStream(state->stream_id);
      state->Finish(err);
    }
  };
  handler.on_trailers = [state](std::vector<Header>&& hs) {
    state->ScanStatus(hs);
  };
  handler.on_close = [state](Error err) { state->Finish(err); };

  int32_t stream_id = 0;
  Error err = conn_->StartStream(
      &stream_id, headers, std::move(handler), /*end_stream=*/false);
  if (!err.IsOk()) {
    return err;
  }
  state->stream_id = stream_id;
  call->state_ = std::move(state);
  return Error::Success;
}

Error
GrpcChannel::Unary(
    const std::string& service, const std::string& method,
    const std::string& request, std::string* response, uint64_t timeout_us,
    const std::vector<Header>& extra_headers)
{
  struct Sync {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    Error err;
    int status = -1;
    std::string status_message;
    std::string response;
  };
  auto sync = std::make_shared<Sync>();

  GrpcCall call;
  Error err = StartCall(
      &call, service, method,
      [sync](std::string&& msg) {
        std::lock_guard<std::mutex> lk(sync->mu);
        sync->response = std::move(msg);
      },
      [sync](Error e, int status, std::string message) {
        std::lock_guard<std::mutex> lk(sync->mu);
        sync->err = e;
        sync->status = status;
        sync->status_message = std::move(message);
        sync->done = true;
        sync->cv.notify_all();
      },
      timeout_us, extra_headers);
  if (!err.IsOk()) {
    return err;
  }
  err = call.Write(request, /*end_of_calls=*/true);
  if (!err.IsOk()) {
    return err;
  }

  std::unique_lock<std::mutex> lk(sync->mu);
  if (timeout_us > 0) {
    // client-side deadline on top of the grpc-timeout header
    if (!sync->cv.wait_for(
            lk, std::chrono::microseconds(timeout_us + 100000),
            [&]() { return sync->done; })) {
      lk.unlock();
      call.Cancel();
      return Error("Deadline Exceeded");
    }
  } else {
    sync->cv.wait(lk, [&]() { return sync->done; });
  }
  if (!sync->err.IsOk()) {
    return sync->err;
  }
  if (sync->status != 0) {
    std::string msg = sync->status_message.empty()
                          ? ("grpc-status " + std::to_string(sync->status))
                          : sync->status_message;
    if (sync->status == 4) {
      msg = "Deadline Exceeded: " + msg;
    }
    return Error(msg);
  }
  *response = std::move(sync->response);
  return Error::Success;
}

}  // namespace h2
}  // namespace tc
