// C shim exposing POSIX shared-memory primitives to the Python client via
// ctypes.  TPU-native rebuild of the role played by the reference's
// libcshm.so (reference src/python/library/tritonclient/utils/shared_memory/
// shared_memory.cc:74-79): create/open/map system shm regions that a
// co-located inference server can register and read/write with zero
// serialization.
//
// Error codes: 0 ok, -1 shm_open failed, -2 ftruncate failed, -3 mmap failed,
// -4 munmap/close failed, -5 shm_unlink failed.

#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

int
TpuShmRegionCreate(
    const char* shm_key, size_t byte_size, int* shm_fd_out, void** base_out)
{
  int fd = shm_open(shm_key, O_RDWR | O_CREAT, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return -1;
  }
  if (ftruncate(fd, (off_t)byte_size) == -1) {
    close(fd);
    return -2;
  }
  void* base =
      mmap(nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return -3;
  }
  *shm_fd_out = fd;
  *base_out = base;
  return 0;
}

int
TpuShmRegionOpen(
    const char* shm_key, size_t byte_size, size_t offset, int* shm_fd_out,
    void** base_out)
{
  int fd = shm_open(shm_key, O_RDWR, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return -1;
  }
  void* base = mmap(
      nullptr, offset + byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return -3;
  }
  *shm_fd_out = fd;
  *base_out = base;
  return 0;
}

int
TpuShmRegionSet(
    void* base, size_t offset, size_t byte_size, const void* data)
{
  memcpy((char*)base + offset, data, byte_size);
  return 0;
}

int
TpuShmRegionGet(void* base, size_t offset, size_t byte_size, void* out)
{
  memcpy(out, (char*)base + offset, byte_size);
  return 0;
}

int
TpuShmRegionClose(int shm_fd, void* base, size_t byte_size)
{
  int rc = 0;
  if (munmap(base, byte_size) == -1) {
    rc = -4;
  }
  if (close(shm_fd) == -1) {
    rc = -4;
  }
  return rc;
}

int
TpuShmRegionUnlink(const char* shm_key)
{
  if (shm_unlink(shm_key) == -1) {
    return -5;
  }
  return 0;
}

}  // extern "C"
