#include "common.h"

#include <ostream>

namespace tc {

const Error Error::Success = Error();

std::ostream&
operator<<(std::ostream& out, const Error& err)
{
  if (err.IsOk()) {
    out << "OK";
  } else {
    out << err.Message();
  }
  return out;
}

//==============================================================================

Error
InferInput::Create(
    InferInput** infer_input, const std::string& name,
    const std::vector<int64_t>& dims, const std::string& datatype)
{
  *infer_input = new InferInput(name, dims, datatype);
  return Error::Success;
}

InferInput::InferInput(
    const std::string& name, const std::vector<int64_t>& dims,
    const std::string& datatype)
    : name_(name), shape_(dims), datatype_(datatype)
{
}

Error
InferInput::Reset()
{
  bufs_.clear();
  str_bufs_.clear();
  total_byte_size_ = 0;
  cursor_ = 0;
  shm_name_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success;
}

Error
InferInput::AppendRaw(const uint8_t* input, size_t input_byte_size)
{
  if (!shm_name_.empty()) {
    return Error(
        "The input '" + name_ +
        "' is referencing shared memory; can not append raw data");
  }
  bufs_.emplace_back(input, input_byte_size);
  total_byte_size_ += input_byte_size;
  return Error::Success;
}

Error
InferInput::AppendRaw(const std::vector<uint8_t>& input)
{
  return AppendRaw(input.data(), input.size());
}

Error
InferInput::AppendFromString(const std::vector<std::string>& input)
{
  // serialize as 4-byte little-endian length + bytes, owned by this object
  str_bufs_.emplace_back();
  std::string& serialized = str_bufs_.back();
  for (const auto& s : input) {
    uint32_t len = (uint32_t)s.size();
    serialized.append(reinterpret_cast<const char*>(&len), 4);
    serialized.append(s);
  }
  return AppendRaw(
      reinterpret_cast<const uint8_t*>(serialized.data()),
      serialized.size());
}

Error
InferInput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset)
{
  if (!bufs_.empty()) {
    return Error(
        "The input '" + name_ +
        "' already has raw data; can not reference shared memory");
  }
  shm_name_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success;
}

Error
InferInput::PrepareForRequest()
{
  cursor_ = 0;
  return Error::Success;
}

Error
InferInput::GetNext(
    const uint8_t** buf, size_t* input_bytes, bool* end_of_input)
{
  if (cursor_ < bufs_.size()) {
    *buf = bufs_[cursor_].first;
    *input_bytes = bufs_[cursor_].second;
    ++cursor_;
  } else {
    *buf = nullptr;
    *input_bytes = 0;
  }
  *end_of_input = (cursor_ >= bufs_.size());
  return Error::Success;
}

//==============================================================================

Error
InferRequestedOutput::Create(
    InferRequestedOutput** infer_output, const std::string& name,
    const size_t class_count)
{
  *infer_output = new InferRequestedOutput(name, class_count);
  return Error::Success;
}

InferRequestedOutput::InferRequestedOutput(
    const std::string& name, const size_t class_count)
    : name_(name), class_count_(class_count)
{
}

Error
InferRequestedOutput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset)
{
  shm_name_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success;
}

Error
InferRequestedOutput::UnsetSharedMemory()
{
  shm_name_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success;
}

//==============================================================================

void
InferenceServerClient::UpdateInferStat(const RequestTimers& timer)
{
  std::lock_guard<std::mutex> lk(stat_mu_);
  infer_stat_.completed_request_count++;
  infer_stat_.cumulative_total_request_time_ns += timer.Duration(
      RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END);
  infer_stat_.cumulative_send_time_ns += timer.Duration(
      RequestTimers::Kind::SEND_START, RequestTimers::Kind::SEND_END);
  infer_stat_.cumulative_receive_time_ns += timer.Duration(
      RequestTimers::Kind::RECV_START, RequestTimers::Kind::RECV_END);
}

}  // namespace tc
