// Minimal self-contained JSON DOM: parse + serialize.
//
// Plays the role of the reference's TritonJson/rapidjson layer
// (reference src/c++/library/json_utils.{h,cc}) — neither rapidjson nor
// nlohmann ships in this environment, and the KServe-v2 JSON surface is
// small enough that a compact DOM keeps the client dependency-free.

#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace tc {
namespace json {

class Value;
using ValuePtr = std::shared_ptr<Value>;

enum class Type { Null, Bool, Int, Double, String, Array, Object };

class Value {
 public:
  Value() : type_(Type::Null) {}
  explicit Value(bool b) : type_(Type::Bool), bool_(b) {}
  explicit Value(int64_t i) : type_(Type::Int), int_(i) {}
  explicit Value(uint64_t i) : type_(Type::Int), int_((int64_t)i) {}
  explicit Value(int i) : type_(Type::Int), int_(i) {}
  explicit Value(double d) : type_(Type::Double), double_(d) {}
  explicit Value(const std::string& s) : type_(Type::String), str_(s) {}
  explicit Value(const char* s) : type_(Type::String), str_(s) {}

  static ValuePtr MakeObject() {
    auto v = std::make_shared<Value>();
    v->type_ = Type::Object;
    return v;
  }
  static ValuePtr MakeArray() {
    auto v = std::make_shared<Value>();
    v->type_ = Type::Array;
    return v;
  }

  Type type() const { return type_; }
  bool IsNull() const { return type_ == Type::Null; }
  bool IsNumber() const
  {
    return type_ == Type::Int || type_ == Type::Double;
  }

  bool AsBool() const { return bool_; }
  int64_t AsInt() const
  {
    return type_ == Type::Double ? (int64_t)double_ : int_;
  }
  double AsDouble() const
  {
    return type_ == Type::Int ? (double)int_ : double_;
  }
  const std::string& AsString() const { return str_; }

  // object access
  ValuePtr Get(const std::string& key) const
  {
    auto it = members_.find(key);
    return it == members_.end() ? nullptr : it->second;
  }
  bool Has(const std::string& key) const
  {
    return members_.count(key) > 0;
  }
  void Set(const std::string& key, ValuePtr v) { members_[key] = v; }
  void Set(const std::string& key, const std::string& s)
  {
    members_[key] = std::make_shared<Value>(s);
  }
  void Set(const std::string& key, const char* s)
  {
    members_[key] = std::make_shared<Value>(s);
  }
  void Set(const std::string& key, int64_t i)
  {
    members_[key] = std::make_shared<Value>(i);
  }
  void Set(const std::string& key, uint64_t i)
  {
    members_[key] = std::make_shared<Value>(i);
  }
  void Set(const std::string& key, int i)
  {
    members_[key] = std::make_shared<Value>(i);
  }
  void Set(const std::string& key, double d)
  {
    members_[key] = std::make_shared<Value>(d);
  }
  void Set(const std::string& key, bool b)
  {
    members_[key] = std::make_shared<Value>(b);
  }
  const std::map<std::string, ValuePtr>& Members() const
  {
    return members_;
  }

  // array access
  void Append(ValuePtr v) { elements_.push_back(v); }
  size_t Size() const { return elements_.size(); }
  ValuePtr At(size_t i) const
  {
    return i < elements_.size() ? elements_[i] : nullptr;
  }
  const std::vector<ValuePtr>& Elements() const { return elements_; }

  std::string Serialize() const;

 private:
  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string str_;
  std::vector<ValuePtr> elements_;
  std::map<std::string, ValuePtr> members_;
};

// Parse JSON text; returns nullptr and sets *error on failure.
ValuePtr Parse(const std::string& text, std::string* error);

// Append `s` to *out as a quoted, escaped JSON string literal.
void EscapeTo(const std::string& s, std::string* out);

}  // namespace json
}  // namespace tc
