// gRPC client for the KServe-v2 protocol.
//
// Re-design of the reference InferenceServerGrpcClient (reference
// src/c++/library/grpc_client.h:100-570, grpc_client.cc) for the
// TPU-native stack.  The reference rides grpc++; this image has no
// grpc++ headers, so the transport is the in-tree HTTP/2 + gRPC framing
// layer (h2/grpc_channel.h) — full wire compatibility with any gRPC
// server, verified against grpcio in the test suite.  Same public
// surface: channel cache with share count (reference grpc_client.cc:
// 78-145), sync Infer, AsyncInfer on a callback worker (role of the
// completion-queue AsyncTransfer thread, grpc_client.cc:1483-1527),
// InferMulti/AsyncInferMulti, bidirectional ModelStreamInfer streaming
// (grpc_client.cc:1240-1336), and the full non-infer verb set including
// the XLA shared-memory extension in place of the CUDA verbs
// (grpc_client.h:365-390).

#pragma once

#include <atomic>
#include <condition_variable>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <thread>

#include "common.h"
#include "grpc_service.pb.h"
#include "h2/grpc_channel.h"

namespace tc {

using OnMultiCompleteFn = std::function<void(std::vector<InferResult*>)>;

//==============================================================================
// SSL/keepalive option structs (API parity, reference grpc_client.h:43-82).
// use_ssl upgrades the h2 transport to TLS (ALPN "h2") via the dlopen'd
// OpenSSL engine in tls.h; the SslOptions fields are PEM file paths, like
// the reference's.  Keepalive maps onto h2 PING: a keepalive thread
// pings every keepalive_time_ms (when < INT32_MAX) and treats a missed
// ack within keepalive_timeout_ms as connection death; pings pause after
// http2_max_pings_without_data consecutive pings with no intervening
// calls, mirroring gRPC's too_many_pings protection.
//
struct SslOptions {
  std::string root_certificates;
  std::string private_key;
  std::string certificate_chain;
};

struct KeepAliveOptions {
  int keepalive_time_ms = INT32_MAX;
  int keepalive_timeout_ms = 20000;
  bool keepalive_permit_without_calls = false;
  int http2_max_pings_without_data = 2;
};

//==============================================================================
// Result of a gRPC inference (reference grpc_client.cc:170-232).
//
class InferResultGrpc : public InferResult {
 public:
  static Error Create(
      InferResult** infer_result,
      std::shared_ptr<inference::ModelInferResponse> response);
  // streaming variant: carries the stream-level error message, if any
  static Error Create(
      InferResult** infer_result,
      std::shared_ptr<inference::ModelStreamInferResponse> stream_response);

  Error ModelName(std::string* name) const override;
  Error ModelVersion(std::string* version) const override;
  Error Id(std::string* id) const override;
  Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const override;
  Error Datatype(
      const std::string& output_name, std::string* datatype) const override;
  Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const override;
  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override;
  std::string DebugString() const override;
  Error RequestStatus() const override;

  const inference::ModelInferResponse& Response() const { return *response_; }
  void SetRequestStatus(const Error& status) { status_ = status; }

  // True when the response carries triton_final_response=true, or when
  // it carries no final marker at all (unary / non-decoupled responses
  // are implicitly final).
  bool IsFinalResponse() const
  {
    auto it = response_->parameters().find("triton_final_response");
    if (it == response_->parameters().end()) {
      return true;
    }
    return it->second.bool_param();
  }
  // True when the final marker parameter is present (decoupled streams
  // requested with triton_enable_empty_final_response).
  bool HasFinalMarker() const
  {
    return response_->parameters().count("triton_final_response") > 0;
  }

 private:
  InferResultGrpc(std::shared_ptr<inference::ModelInferResponse> response);
  Error Output(
      const std::string& name,
      const inference::ModelInferResponse::InferOutputTensor** tensor,
      size_t* index) const;

  std::shared_ptr<inference::ModelInferResponse> response_;
  std::shared_ptr<inference::ModelStreamInferResponse> stream_response_;
  Error status_;
};

//==============================================================================
class InferenceServerGrpcClient : public InferenceServerClient {
 public:
  // Channels to the same url are shared between clients up to a share
  // count of 6, overridable via TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT
  // (reference grpc_client.cc:78-145).
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& server_url, bool verbose = false,
      bool use_ssl = false, const SslOptions& ssl_options = SslOptions(),
      const KeepAliveOptions& keepalive_options = KeepAliveOptions());

  ~InferenceServerGrpcClient();

  Error IsServerLive(bool* live);
  Error IsServerReady(bool* ready);
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "");

  Error ServerMetadata(inference::ServerMetadataResponse* server_metadata);
  Error ModelMetadata(
      inference::ModelMetadataResponse* model_metadata,
      const std::string& model_name, const std::string& model_version = "");
  Error ModelConfig(
      inference::ModelConfigResponse* model_config,
      const std::string& model_name, const std::string& model_version = "");

  Error ModelRepositoryIndex(
      inference::RepositoryIndexResponse* repository_index);
  Error LoadModel(
      const std::string& model_name, const std::string& config = "");
  Error UnloadModel(const std::string& model_name);

  Error ModelInferenceStatistics(
      inference::ModelStatisticsResponse* infer_stat,
      const std::string& model_name = "",
      const std::string& model_version = "");

  // Per-message compression for Infer/AsyncInfer/stream requests:
  // "" or "none" (identity, default), "gzip", "deflate".  Role of the
  // reference's grpc_compression_algorithm context setting
  // (reference grpc_client.cc:1380-1389); responses auto-detect either
  // algorithm whenever the server sets the compressed flag.  Unknown
  // algorithms error here — silently mislabeling the wire encoding
  // would surface as confusing server-side decode failures.
  Error SetInferCompression(const std::string& algorithm)
  {
    if (algorithm != "" && algorithm != "none" && algorithm != "gzip" &&
        algorithm != "deflate") {
      return Error(
          "unsupported compression algorithm '" + algorithm +
          "' (expected none|gzip|deflate)");
    }
    infer_compression_ = (algorithm == "none") ? "" : algorithm;
    return Error::Success;
  }

  Error UpdateTraceSettings(
      inference::TraceSettingResponse* response,
      const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {});
  Error GetTraceSettings(
      inference::TraceSettingResponse* settings,
      const std::string& model_name = "");

  // values: "true"/"false" -> bool, decimal -> uint32, else string
  Error UpdateLogSettings(
      inference::LogSettingsResponse* response,
      const std::map<std::string, std::string>& settings);
  Error GetLogSettings(inference::LogSettingsResponse* settings);

  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0);
  Error UnregisterSystemSharedMemory(const std::string& name = "");
  Error SystemSharedMemoryStatus(
      inference::SystemSharedMemoryStatusResponse* status);

  // XLA/TPU shared memory (generalization of reference grpc_client.h:
  // 365-390): raw_handle is the serialized handle from the
  // xla_shared_memory utility library.
  Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal = 0);
  Error UnregisterXlaSharedMemory(const std::string& name = "");
  Error XlaSharedMemoryStatus(inference::XlaSharedMemoryStatusResponse* status);

  Error RegisterCudaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_id = 0);
  Error UnregisterCudaSharedMemory(const std::string& name = "");
  Error CudaSharedMemoryStatus(
      inference::CudaSharedMemoryStatusResponse* status);

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs =
          std::vector<const InferRequestedOutput*>());

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs =
          std::vector<const InferRequestedOutput*>());

  // Issue several requests, collecting every result (reference
  // grpc_client.cc:1130-1239).
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          std::vector<std::vector<const InferRequestedOutput*>>());
  Error AsyncInferMulti(
      OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          std::vector<std::vector<const InferRequestedOutput*>>());

  // Bidirectional ModelStreamInfer (reference grpc_client.cc:1240-1336).
  // stream_callback fires per response on the stream worker thread.
  Error StartStream(
      OnCompleteFn stream_callback, bool enable_stats = true,
      uint64_t stream_timeout_us = 0);
  Error StopStream();
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs =
          std::vector<const InferRequestedOutput*>());

  // Observability hook for the keepalive path (tests assert pings flow):
  // number of keepalive PING round-trips acknowledged by the server.
  uint64_t KeepAlivePingCount() const { return keepalive_pings_.load(); }

 private:
  InferenceServerGrpcClient(
      std::shared_ptr<h2::GrpcChannel> channel, bool verbose,
      const KeepAliveOptions& keepalive_options);

  template <typename Req, typename Resp>
  Error Rpc(
      const std::string& method, const Req& request, Resp* response,
      uint64_t timeout_us = 0);

  // Fill the (reused) request protobuf from inputs/outputs/options —
  // role of the reference's PreRunProcessing (grpc_client.cc:1338-1481).
  Error PreRunProcessing(
      inference::ModelInferRequest* request, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs);

  void DispatchWorker();
  void EnqueueCallback(std::function<void()> fn);
  void KeepAliveWorker();

  std::vector<h2::Header> CompressionHeaders() const
  {
    if (infer_compression_.empty()) {
      return {};
    }
    return {{"grpc-encoding", infer_compression_}};
  }

  std::string infer_compression_;

  std::shared_ptr<h2::GrpcChannel> channel_;
  // reused protobuf for sync Infer (reference's protobuf-reuse
  // optimization, grpc_client.cc:1342-1348)
  inference::ModelInferRequest sync_request_;

  // async + stream callback dispatch worker
  std::mutex worker_mu_;
  std::condition_variable worker_cv_;
  std::deque<std::function<void()>> worker_queue_;
  std::thread worker_;
  bool worker_exit_ = false;

  // active stream state
  std::mutex stream_mu_;
  std::unique_ptr<h2::GrpcCall> stream_call_;
  OnCompleteFn stream_callback_;
  bool stream_enable_stats_ = true;
  std::deque<RequestTimers> stream_timers_;  // FIFO request->response match;
                                             // decoupled responses have no
                                             // 1:1 mapping (reference
                                             // grpc_client.cc:1551-1554)
  bool stream_done_ = false;
  Error stream_status_;
  std::condition_variable stream_cv_;

  // in-flight AsyncInfer tracking: the destructor cancels and drains
  // these before tearing down the dispatch worker, so reader-thread
  // completions never touch a destroyed client.
  std::mutex async_mu_;
  std::condition_variable async_cv_;
  size_t outstanding_async_ = 0;
  uint64_t next_async_id_ = 0;
  std::map<uint64_t, h2::GrpcCall> outstanding_calls_;

  // keepalive (h2 PING) worker
  KeepAliveOptions keepalive_options_;
  std::thread keepalive_thread_;
  std::mutex keepalive_mu_;
  std::condition_variable keepalive_cv_;
  bool keepalive_exit_ = false;
  std::atomic<uint64_t> keepalive_pings_{0};
  std::atomic<uint64_t> call_activity_{0};  // bumped per issued call

};

}  // namespace tc
