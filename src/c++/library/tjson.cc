#include "tjson.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace tc {
namespace json {

namespace {

struct Parser {
  const char* p;
  const char* end;
  std::string* error;

  void SkipWs()
  {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      ++p;
  }

  bool Fail(const std::string& msg)
  {
    if (error->empty()) {
      *error = msg;
    }
    return false;
  }

  bool ParseValue(ValuePtr* out)
  {
    SkipWs();
    if (p >= end) {
      return Fail("unexpected end of input");
    }
    switch (*p) {
      case '{':
        return ParseObject(out);
      case '[':
        return ParseArray(out);
      case '"': {
        std::string s;
        if (!ParseString(&s)) {
          return false;
        }
        *out = std::make_shared<Value>(s);
        return true;
      }
      case 't':
        if (end - p >= 4 && strncmp(p, "true", 4) == 0) {
          p += 4;
          *out = std::make_shared<Value>(true);
          return true;
        }
        return Fail("invalid literal");
      case 'f':
        if (end - p >= 5 && strncmp(p, "false", 5) == 0) {
          p += 5;
          *out = std::make_shared<Value>(false);
          return true;
        }
        return Fail("invalid literal");
      case 'n':
        if (end - p >= 4 && strncmp(p, "null", 4) == 0) {
          p += 4;
          *out = std::make_shared<Value>();
          return true;
        }
        return Fail("invalid literal");
      default:
        return ParseNumber(out);
    }
  }

  bool ParseString(std::string* out)
  {
    if (*p != '"') {
      return Fail("expected string");
    }
    ++p;
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) {
          return Fail("bad escape");
        }
        switch (*p) {
          case '"':
            out->push_back('"');
            break;
          case '\\':
            out->push_back('\\');
            break;
          case '/':
            out->push_back('/');
            break;
          case 'b':
            out->push_back('\b');
            break;
          case 'f':
            out->push_back('\f');
            break;
          case 'n':
            out->push_back('\n');
            break;
          case 'r':
            out->push_back('\r');
            break;
          case 't':
            out->push_back('\t');
            break;
          case 'u': {
            if (end - p < 5) {
              return Fail("bad \\u escape");
            }
            unsigned code = 0;
            for (int i = 1; i <= 4; ++i) {
              char c = p[i];
              code <<= 4;
              if (c >= '0' && c <= '9')
                code |= (unsigned)(c - '0');
              else if (c >= 'a' && c <= 'f')
                code |= (unsigned)(c - 'a' + 10);
              else if (c >= 'A' && c <= 'F')
                code |= (unsigned)(c - 'A' + 10);
              else
                return Fail("bad \\u escape");
            }
            p += 4;
            // UTF-8 encode (BMP only; surrogate pairs unsupported — the v2
            // protocol carries tensor data in binary sections, not JSON)
            if (code < 0x80) {
              out->push_back((char)code);
            } else if (code < 0x800) {
              out->push_back((char)(0xC0 | (code >> 6)));
              out->push_back((char)(0x80 | (code & 0x3F)));
            } else {
              out->push_back((char)(0xE0 | (code >> 12)));
              out->push_back((char)(0x80 | ((code >> 6) & 0x3F)));
              out->push_back((char)(0x80 | (code & 0x3F)));
            }
            break;
          }
          default:
            return Fail("bad escape");
        }
        ++p;
      } else {
        out->push_back(*p);
        ++p;
      }
    }
    if (p >= end) {
      return Fail("unterminated string");
    }
    ++p;  // closing quote
    return true;
  }

  bool ParseNumber(ValuePtr* out)
  {
    const char* start = p;
    if (p < end && (*p == '-' || *p == '+')) {
      ++p;
    }
    bool is_double = false;
    while (p < end &&
           (isdigit((unsigned char)*p) || *p == '.' || *p == 'e' ||
            *p == 'E' || *p == '-' || *p == '+')) {
      if (*p == '.' || *p == 'e' || *p == 'E') {
        is_double = true;
      }
      ++p;
    }
    if (p == start) {
      return Fail("invalid number");
    }
    std::string tok(start, p - start);
    try {
      if (is_double) {
        *out = std::make_shared<Value>(std::stod(tok));
      } else {
        *out = std::make_shared<Value>((int64_t)std::stoll(tok));
      }
    }
    catch (...) {
      return Fail("invalid number '" + tok + "'");
    }
    return true;
  }

  bool ParseObject(ValuePtr* out)
  {
    ++p;  // '{'
    auto obj = Value::MakeObject();
    SkipWs();
    if (p < end && *p == '}') {
      ++p;
      *out = obj;
      return true;
    }
    while (true) {
      SkipWs();
      std::string key;
      if (!ParseString(&key)) {
        return false;
      }
      SkipWs();
      if (p >= end || *p != ':') {
        return Fail("expected ':'");
      }
      ++p;
      ValuePtr v;
      if (!ParseValue(&v)) {
        return false;
      }
      obj->Set(key, v);
      SkipWs();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == '}') {
        ++p;
        *out = obj;
        return true;
      }
      return Fail("expected ',' or '}'");
    }
  }

  bool ParseArray(ValuePtr* out)
  {
    ++p;  // '['
    auto arr = Value::MakeArray();
    SkipWs();
    if (p < end && *p == ']') {
      ++p;
      *out = arr;
      return true;
    }
    while (true) {
      ValuePtr v;
      if (!ParseValue(&v)) {
        return false;
      }
      arr->Append(v);
      SkipWs();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == ']') {
        ++p;
        *out = arr;
        return true;
      }
      return Fail("expected ',' or ']'");
    }
  }
};

}  // namespace

void
EscapeTo(const std::string& s, std::string* out)
{
  out->push_back('"');
  for (char c : s) {
    switch (c) {
      case '"':
        out->append("\\\"");
        break;
      case '\\':
        out->append("\\\\");
        break;
      case '\n':
        out->append("\\n");
        break;
      case '\r':
        out->append("\\r");
        break;
      case '\t':
        out->append("\\t");
        break;
      default:
        if ((unsigned char)c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          out->append(buf);
        } else {
          out->push_back(c);
        }
    }
  }
  out->push_back('"');
}

namespace {

void
SerializeTo(const Value& v, std::string* out)
{
  switch (v.type()) {
    case Type::Null:
      out->append("null");
      break;
    case Type::Bool:
      out->append(v.AsBool() ? "true" : "false");
      break;
    case Type::Int:
      out->append(std::to_string(v.AsInt()));
      break;
    case Type::Double: {
      double d = v.AsDouble();
      if (std::isfinite(d)) {
        char buf[32];
        snprintf(buf, sizeof(buf), "%.17g", d);
        out->append(buf);
      } else {
        out->append("null");
      }
      break;
    }
    case Type::String:
      EscapeTo(v.AsString(), out);
      break;
    case Type::Array: {
      out->push_back('[');
      bool first = true;
      for (const auto& e : v.Elements()) {
        if (!first) {
          out->push_back(',');
        }
        first = false;
        SerializeTo(*e, out);
      }
      out->push_back(']');
      break;
    }
    case Type::Object: {
      out->push_back('{');
      bool first = true;
      for (const auto& kv : v.Members()) {
        if (!first) {
          out->push_back(',');
        }
        first = false;
        EscapeTo(kv.first, out);
        out->push_back(':');
        SerializeTo(*kv.second, out);
      }
      out->push_back('}');
      break;
    }
  }
}

}  // namespace

std::string
Value::Serialize() const
{
  std::string out;
  SerializeTo(*this, &out);
  return out;
}

ValuePtr
Parse(const std::string& text, std::string* error)
{
  std::string err;
  Parser parser{text.data(), text.data() + text.size(), &err};
  ValuePtr v;
  if (!parser.ParseValue(&v)) {
    if (error) {
      *error = err.empty() ? "parse error" : err;
    }
    return nullptr;
  }
  if (error) {
    error->clear();
  }
  return v;
}

}  // namespace json
}  // namespace tc
