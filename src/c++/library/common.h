// Common client types: Error, request options, tensor descriptors, result
// interface, timing.  Re-design of the reference C++ client core
// (reference src/c++/library/common.h:62-628) for the TPU-native stack —
// same public surface, fresh implementation, no CUDA anywhere: the
// device-memory plane is XLA shared memory (region names + serialized
// handles), never raw device pointers.

#pragma once

#include <chrono>
#include <deque>
#include <cstdint>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <string>
#include <vector>
#include <mutex>

namespace tc {

//==============================================================================
// Error status returned by all client calls (reference common.h:62-84).
//
class Error {
 public:
  Error() : ok_(true) {}
  explicit Error(const std::string& msg) : ok_(false), msg_(msg) {}

  static const Error Success;

  bool IsOk() const { return ok_; }
  const std::string& Message() const { return msg_; }

 private:
  bool ok_;
  std::string msg_;
};

std::ostream& operator<<(std::ostream&, const Error&);

//==============================================================================
// Per-request options (reference common.h:159-222).
//
struct InferOptions {
  explicit InferOptions(const std::string& model_name)
      : model_name_(model_name), model_version_(""), request_id_(""),
        sequence_id_(0), sequence_start_(false), sequence_end_(false),
        priority_(0), server_timeout_us_(0), client_timeout_us_(0)
  {
  }

  std::string model_name_;
  std::string model_version_;
  std::string request_id_;
  uint64_t sequence_id_;
  bool sequence_start_;
  bool sequence_end_;
  uint64_t priority_;
  // server-side timeout parameter; 0 = none
  uint64_t server_timeout_us_;
  // client-side socket deadline; 0 = none
  uint64_t client_timeout_us_;
  // ask a decoupled model for a trailing empty response marked
  // triton_final_response, so data-dependent-length streams have a
  // detectable end (KServe v2 parameter; reference uses the same flag
  // in its streaming clients)
  bool triton_enable_empty_final_response_ = false;
};

//==============================================================================
// Client-side aggregate statistics (reference common.h:94-115).
//
struct InferStat {
  size_t completed_request_count = 0;
  uint64_t cumulative_total_request_time_ns = 0;
  uint64_t cumulative_send_time_ns = 0;
  uint64_t cumulative_receive_time_ns = 0;
};

//==============================================================================
// Six-point per-request timer (reference common.h:523-603).
//
class RequestTimers {
 public:
  enum class Kind {
    REQUEST_START,
    REQUEST_END,
    SEND_START,
    SEND_END,
    RECV_START,
    RECV_END,
    COUNT_
  };

  RequestTimers() { Reset(); }

  void Reset()
  {
    for (auto& t : stamps_) {
      t = 0;
    }
  }

  void CaptureTimestamp(Kind kind)
  {
    stamps_[(size_t)kind] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count();
  }

  uint64_t Timestamp(Kind kind) const { return stamps_[(size_t)kind]; }

  uint64_t Duration(Kind start, Kind end) const
  {
    uint64_t s = stamps_[(size_t)start], e = stamps_[(size_t)end];
    return (s == 0 || e == 0 || e < s) ? 0 : e - s;
  }

 private:
  uint64_t stamps_[(size_t)Kind::COUNT_];
};

//==============================================================================
// An input tensor (reference common.h:228-367).  Data is referenced, not
// copied: AppendRaw keeps (ptr, size) pairs and the transport scatter-
// gathers them onto the wire; SetSharedMemory references a registered
// region instead of carrying bytes.
//
class InferInput {
 public:
  static Error Create(
      InferInput** infer_input, const std::string& name,
      const std::vector<int64_t>& dims, const std::string& datatype);

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  const std::vector<int64_t>& Shape() const { return shape_; }
  Error SetShape(const std::vector<int64_t>& dims)
  {
    shape_ = dims;
    return Error::Success;
  }

  Error Reset();
  Error AppendRaw(const uint8_t* input, size_t input_byte_size);
  Error AppendRaw(const std::vector<uint8_t>& input);
  // BYTES convenience: 4-byte length-prefixed serialization
  Error AppendFromString(const std::vector<std::string>& input);

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  bool IsSharedMemory() const { return !shm_name_.empty(); }
  const std::string& SharedMemoryName() const { return shm_name_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

  size_t TotalByteSize() const { return total_byte_size_; }

  // Scatter-gather iteration over the raw buffers (reference
  // common.h:350-360): resets then returns each (buf, len) chunk.
  Error PrepareForRequest();
  Error GetNext(const uint8_t** buf, size_t* input_bytes, bool* end_of_input);

 private:
  InferInput(
      const std::string& name, const std::vector<int64_t>& dims,
      const std::string& datatype);

  std::string name_;
  std::vector<int64_t> shape_;
  std::string datatype_;
  size_t total_byte_size_ = 0;
  std::vector<std::pair<const uint8_t*, size_t>> bufs_;
  // owned storage for AppendFromString; deque: growth never moves
  // existing elements, so (ptr,size) entries in bufs_ stay valid
  std::deque<std::string> str_bufs_;
  size_t cursor_ = 0;
  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

//==============================================================================
// A requested output (reference common.h:373-445).
//
class InferRequestedOutput {
 public:
  static Error Create(
      InferRequestedOutput** infer_output, const std::string& name,
      const size_t class_count = 0);

  const std::string& Name() const { return name_; }
  size_t ClassCount() const { return class_count_; }
  bool BinaryData() const { return binary_data_; }
  Error SetBinaryData(bool binary_data)
  {
    binary_data_ = binary_data;
    return Error::Success;
  }

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  Error UnsetSharedMemory();
  bool IsSharedMemory() const { return !shm_name_.empty(); }
  const std::string& SharedMemoryName() const { return shm_name_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

 private:
  InferRequestedOutput(const std::string& name, const size_t class_count);

  std::string name_;
  size_t class_count_;
  bool binary_data_ = true;
  std::string shm_name_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

//==============================================================================
// Result interface (reference common.h:451-518).
//
class InferResult {
 public:
  virtual ~InferResult() = default;

  virtual Error ModelName(std::string* name) const = 0;
  virtual Error ModelVersion(std::string* version) const = 0;
  virtual Error Id(std::string* id) const = 0;
  virtual Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const = 0;
  virtual Error Datatype(
      const std::string& output_name, std::string* datatype) const = 0;
  virtual Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const = 0;
  // BYTES tensor deserialization (4-byte length prefix)
  virtual Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const = 0;
  virtual std::string DebugString() const = 0;
  virtual Error RequestStatus() const = 0;
};

using OnCompleteFn = std::function<void(InferResult*)>;

//==============================================================================
// Shared base: stat aggregation (reference common.h:120-154).
//
class InferenceServerClient {
 public:
  explicit InferenceServerClient(bool verbose)
      : verbose_(verbose), exiting_(false)
  {
  }
  virtual ~InferenceServerClient() = default;

  Error ClientInferStat(InferStat* infer_stat) const
  {
    std::lock_guard<std::mutex> lk(stat_mu_);
    *infer_stat = infer_stat_;
    return Error::Success;
  }

 protected:
  void UpdateInferStat(const RequestTimers& timer);

  bool verbose_;
  bool exiting_;
  // async workers complete requests concurrently; the aggregate is
  // guarded (reference serializes via its worker thread, common.h:135)
  mutable std::mutex stat_mu_;
  InferStat infer_stat_;
};

}  // namespace tc
