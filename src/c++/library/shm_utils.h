// POSIX shared-memory helpers for the C++ client examples/tools
// (reference src/c++/library/shm_utils.{h,cc}:37-105).

#pragma once

#include <string>

#include "common.h"

namespace tc {

// Create a shared-memory region (shm_open + ftruncate); returns its fd.
Error CreateSharedMemoryRegion(
    const std::string& shm_key, size_t byte_size, int* shm_fd);

// Map byte_size bytes at offset of an open region into *shm_addr.
Error MapSharedMemory(
    int shm_fd, size_t offset, size_t byte_size, void** shm_addr);

// Close a region fd.
Error CloseSharedMemory(int shm_fd);

// Remove the named region from the system.
Error UnlinkSharedMemoryRegion(const std::string& shm_key);

// Unmap a previously mapped window.
Error UnmapSharedMemory(void* shm_addr, size_t byte_size);

}  // namespace tc
