// TLS transport for the raw-socket HTTP client and the h2/gRPC channel.
//
// The reference stack gets TLS for free from libcurl / grpc++
// (reference src/c++/library/http_client.cc:253-280 SetSSLCurlOptions,
// grpc_client.cc:78-145 SslCredentials); this image has neither, nor
// OpenSSL headers — but it does ship libssl.so.3/libcrypto.so.3.  So,
// mirroring the dlopen-MPI approach (perf_analyzer/mpi_utils.cc), the
// needed OpenSSL 3 entry points are dlopen'd and declared by hand, and a
// TlsSession wraps an already-connected fd with handshake + read/write.
// Both transports stay single-code-path: they talk to the socket through
// Send/Recv here whether or not TLS is on.

#pragma once

#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace tc {

// Transport-neutral TLS settings, filled from the protocol-specific
// option structs (HttpSslOptions / SslOptions).
struct TlsOptions {
  bool enabled = false;
  // PEM file with trusted roots; empty = OpenSSL default verify paths.
  std::string ca_file;
  // Client certificate chain + private key (PEM), both optional.
  std::string cert_file;
  std::string key_file;
  // Verify the server certificate chain / that the cert matches the
  // host name (reference semantics: CURLOPT_SSL_VERIFYPEER/-HOST).
  bool verify_peer = true;
  bool verify_host = true;
  // ALPN protocols to offer, e.g. {"h2"} for gRPC; empty offers none.
  std::vector<std::string> alpn;
};

// One TLS client session over a connected socket.  Blocking; honors the
// fd's SO_RCVTIMEO/SO_SNDTIMEO (a timeout surfaces as -1 with
// errno=EAGAIN from Recv/Send, like the plain socket would).
class TlsSession {
 public:
  // Is libssl available in this process? (dlopen on first call)
  static bool Available(std::string* why = nullptr);

  // Wrap ``fd`` (already connected): build a context from ``opts``,
  // send SNI for ``host``, handshake, and verify per opts.  On error the
  // fd is left open (caller owns it).
  static Error Handshake(
      std::unique_ptr<TlsSession>* session, int fd, const TlsOptions& opts,
      const std::string& host);

  ~TlsSession();
  TlsSession(const TlsSession&) = delete;
  TlsSession& operator=(const TlsSession&) = delete;

  // write/read semantics of send/recv: bytes moved, or -1 with errno.
  ssize_t Send(const void* buf, size_t len);
  ssize_t Recv(void* buf, size_t len);

  // Protocol the server selected via ALPN ("" when none).
  const std::string& SelectedAlpn() const { return alpn_; }

  // Best-effort close_notify (does not close the fd).
  void ShutdownNotify();

 private:
  TlsSession() = default;
  void* ssl_ = nullptr;  // SSL*
  void* ctx_ = nullptr;  // SSL_CTX*
  std::string alpn_;
};

// Full-duplex TLS for the h2 transport: one reader thread blocks in
// Recv while writer threads call SendAll concurrently.  A single
// blocking SSL* cannot do that (the object is not thread-safe), so the
// socket runs non-blocking and every engine call happens under a
// short-held mutex; blocking semantics are rebuilt with poll() OUTSIDE
// the lock, so a stalled reader never starves writers or vice versa.
class TlsDuplex {
 public:
  // Puts ``fd`` in non-blocking mode and handshakes (bounded by
  // ``handshake_timeout_ms``).
  static Error Handshake(
      std::unique_ptr<TlsDuplex>* duplex, int fd, const TlsOptions& opts,
      const std::string& host, int handshake_timeout_ms = 30000);

  ~TlsDuplex();
  TlsDuplex(const TlsDuplex&) = delete;
  TlsDuplex& operator=(const TlsDuplex&) = delete;

  // Write the whole buffer (the h2 layer serializes senders itself).
  Error SendAll(const uint8_t* data, size_t len);
  // Block until >=1 byte of plaintext (or 0 on clean close, -1 errno).
  ssize_t Recv(uint8_t* buf, size_t len);

  const std::string& SelectedAlpn() const { return alpn_; }
  void ShutdownNotify();

 private:
  TlsDuplex() = default;
  void* ssl_ = nullptr;
  void* ctx_ = nullptr;
  int fd_ = -1;
  std::string alpn_;
  // guards every SSL_* call; never held across poll()
  std::mutex engine_mu_;
};

}  // namespace tc
