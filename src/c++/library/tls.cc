// dlopen'd OpenSSL 3 TLS session (see tls.h for the design rationale).

#include "tls.h"

#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <poll.h>

#include <chrono>
#include <cstdio>
#include <cstring>
#include <mutex>

namespace tc {

namespace {

// Minimal OpenSSL 3 surface, resolved at runtime.  Types are opaque
// pointers; constants below match the stable public ABI.
struct SslApi {
  int (*OPENSSL_init_ssl)(uint64_t, const void*);
  const void* (*TLS_client_method)();
  void* (*SSL_CTX_new)(const void*);
  void (*SSL_CTX_free)(void*);
  int (*SSL_CTX_load_verify_locations)(void*, const char*, const char*);
  int (*SSL_CTX_set_default_verify_paths)(void*);
  int (*SSL_CTX_use_certificate_chain_file)(void*, const char*);
  int (*SSL_CTX_use_PrivateKey_file)(void*, const char*, int);
  void (*SSL_CTX_set_verify)(void*, int, void*);
  int (*SSL_CTX_set_alpn_protos)(void*, const unsigned char*, unsigned);
  void* (*SSL_new)(void*);
  void (*SSL_free)(void*);
  int (*SSL_set_fd)(void*, int);
  int (*SSL_connect)(void*);
  int (*SSL_read)(void*, void*, int);
  int (*SSL_write)(void*, const void*, int);
  int (*SSL_shutdown)(void*);
  int (*SSL_get_error)(const void*, int);
  int (*SSL_pending)(const void*);
  long (*SSL_ctrl)(void*, int, long, void*);
  long (*SSL_CTX_ctrl)(void*, int, long, void*);
  int (*SSL_set1_host)(void*, const char*);
  void* (*SSL_get0_param)(void*);
  int (*X509_VERIFY_PARAM_set1_ip_asc)(void*, const char*);
  void (*SSL_get0_alpn_selected)(
      const void*, const unsigned char**, unsigned*);
  unsigned long (*ERR_get_error)();
  void (*ERR_error_string_n)(unsigned long, char*, size_t);

  void* libssl = nullptr;
  void* libcrypto = nullptr;
  bool ok = false;
  std::string why;
};

// public ABI constants (openssl/ssl.h, openssl/tls1.h)
constexpr int kSslErrorWantRead = 2;
constexpr int kSslErrorWantWrite = 3;
constexpr int kSslErrorSyscall = 5;
constexpr int kSslFiletypePem = 1;
constexpr int kSslVerifyNone = 0;
constexpr int kSslVerifyPeer = 1;
constexpr int kSslCtrlSetTlsextHostname = 55;
constexpr long kTlsextNametypeHostName = 0;
constexpr int kSslCtrlMode = 33;
// ENABLE_PARTIAL_WRITE | ACCEPT_MOVING_WRITE_BUFFER: non-blocking
// writers may retry from advanced buffer positions
constexpr long kSslModeNonblockWrite = 0x1 | 0x2;

SslApi&
Api()
{
  static SslApi api;
  static std::once_flag once;
  std::call_once(once, []() {
    // libcrypto first: libssl depends on it, and loading it explicitly
    // keeps its symbols resolvable under RTLD_LOCAL
    api.libcrypto = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_GLOBAL);
    if (api.libcrypto == nullptr) {
      api.libcrypto = dlopen("libcrypto.so", RTLD_NOW | RTLD_GLOBAL);
    }
    api.libssl = dlopen("libssl.so.3", RTLD_NOW);
    if (api.libssl == nullptr) {
      api.libssl = dlopen("libssl.so", RTLD_NOW);
    }
    if (api.libssl == nullptr) {
      api.why = std::string("libssl not found: ") + dlerror();
      return;
    }
    auto need = [&](const char* name) -> void* {
      void* sym = dlsym(api.libssl, name);
      if (sym == nullptr && api.libcrypto != nullptr) {
        sym = dlsym(api.libcrypto, name);
      }
      if (sym == nullptr && api.why.empty()) {
        api.why = std::string("missing symbol ") + name;
      }
      return sym;
    };
#define TC_RESOLVE(field) \
  api.field = reinterpret_cast<decltype(api.field)>(need(#field))
    TC_RESOLVE(OPENSSL_init_ssl);
    TC_RESOLVE(TLS_client_method);
    TC_RESOLVE(SSL_CTX_new);
    TC_RESOLVE(SSL_CTX_free);
    TC_RESOLVE(SSL_CTX_load_verify_locations);
    TC_RESOLVE(SSL_CTX_set_default_verify_paths);
    TC_RESOLVE(SSL_CTX_use_certificate_chain_file);
    TC_RESOLVE(SSL_CTX_use_PrivateKey_file);
    TC_RESOLVE(SSL_CTX_set_verify);
    TC_RESOLVE(SSL_CTX_set_alpn_protos);
    TC_RESOLVE(SSL_new);
    TC_RESOLVE(SSL_free);
    TC_RESOLVE(SSL_set_fd);
    TC_RESOLVE(SSL_connect);
    TC_RESOLVE(SSL_read);
    TC_RESOLVE(SSL_write);
    TC_RESOLVE(SSL_shutdown);
    TC_RESOLVE(SSL_get_error);
    TC_RESOLVE(SSL_pending);
    TC_RESOLVE(SSL_ctrl);
    TC_RESOLVE(SSL_CTX_ctrl);
    TC_RESOLVE(SSL_set1_host);
    TC_RESOLVE(SSL_get0_param);
    TC_RESOLVE(X509_VERIFY_PARAM_set1_ip_asc);
    TC_RESOLVE(SSL_get0_alpn_selected);
    TC_RESOLVE(ERR_get_error);
    TC_RESOLVE(ERR_error_string_n);
#undef TC_RESOLVE
    if (!api.why.empty()) {
      return;
    }
    api.OPENSSL_init_ssl(0, nullptr);
    api.ok = true;
  });
  return api;
}

std::string
LastSslError(SslApi& api, const char* what)
{
  char buf[256];
  unsigned long code = api.ERR_get_error();
  if (code == 0) {
    return std::string(what) + ": unknown TLS error";
  }
  api.ERR_error_string_n(code, buf, sizeof(buf));
  // drain the queue so a later call reports its own error
  while (api.ERR_get_error() != 0) {
  }
  return std::string(what) + ": " + buf;
}

// Build an SSL_CTX + SSL for a client connection on ``fd`` per ``opts``
// (CA/cert/key, verify flags, ALPN, SNI + host verification).  Shared by
// the blocking (TlsSession) and full-duplex (TlsDuplex) wrappers.
Error
BuildEngine(
    SslApi& api, const TlsOptions& opts, const std::string& host, int fd,
    void** ctx_out, void** ssl_out)
{
  void*& ctx = *ctx_out;
  void*& ssl = *ssl_out;
  ctx = api.SSL_CTX_new(api.TLS_client_method());
  if (ctx == nullptr) {
    return Error(LastSslError(api, "SSL_CTX_new failed"));
  }
  if (!opts.ca_file.empty()) {
    if (api.SSL_CTX_load_verify_locations(
            ctx, opts.ca_file.c_str(), nullptr) != 1) {
      return Error(
          LastSslError(api, ("loading CA file " + opts.ca_file).c_str()));
    }
  } else {
    api.SSL_CTX_set_default_verify_paths(ctx);
  }
  if (!opts.cert_file.empty()) {
    if (api.SSL_CTX_use_certificate_chain_file(
            ctx, opts.cert_file.c_str()) != 1) {
      return Error(LastSslError(
          api, ("loading client cert " + opts.cert_file).c_str()));
    }
  }
  if (!opts.key_file.empty()) {
    if (api.SSL_CTX_use_PrivateKey_file(
            ctx, opts.key_file.c_str(), kSslFiletypePem) != 1) {
      return Error(LastSslError(
          api, ("loading client key " + opts.key_file).c_str()));
    }
  }
  api.SSL_CTX_set_verify(
      ctx, opts.verify_peer ? kSslVerifyPeer : kSslVerifyNone, nullptr);
  if (!opts.alpn.empty()) {
    // wire format: length-prefixed protocol names
    std::vector<unsigned char> wire;
    for (const auto& proto : opts.alpn) {
      wire.push_back(static_cast<unsigned char>(proto.size()));
      wire.insert(wire.end(), proto.begin(), proto.end());
    }
    // note inverted convention: 0 means success
    if (api.SSL_CTX_set_alpn_protos(
            ctx, wire.data(), (unsigned)wire.size()) != 0) {
      return Error(LastSslError(api, "SSL_CTX_set_alpn_protos failed"));
    }
  }
  ssl = api.SSL_new(ctx);
  if (ssl == nullptr) {
    return Error(LastSslError(api, "SSL_new failed"));
  }
  if (api.SSL_set_fd(ssl, fd) != 1) {
    return Error(LastSslError(api, "SSL_set_fd failed"));
  }
  // SNI (macro SSL_set_tlsext_host_name in the headers); the host part
  // only, certificates never carry ports.  RFC 6066 forbids IP
  // literals in server_name, so skip the extension for them (matching
  // what curl and grpc do); hostname verification for IP endpoints
  // goes through X509_VERIFY_PARAM_set1_ip_asc below (iPAddress SANs;
  // SSL_set1_host only matches dNSName).  IPv6 URL hosts arrive bracketed
  // ("[2001:db8::1]") — strip before the literal check and hostname
  // match, since neither inet_pton nor certificate SANs use brackets.
  std::string bare = host;
  if (bare.size() >= 2 && bare.front() == '[' && bare.back() == ']') {
    bare = bare.substr(1, bare.size() - 2);
  }
  struct in_addr v4;
  struct in6_addr v6;
  const bool ip_literal = inet_pton(AF_INET, bare.c_str(), &v4) == 1 ||
                          inet_pton(AF_INET6, bare.c_str(), &v6) == 1;
  if (!ip_literal) {
    api.SSL_ctrl(
        ssl, kSslCtrlSetTlsextHostname, kTlsextNametypeHostName,
        const_cast<char*>(bare.c_str()));
  }
  if (opts.verify_peer && opts.verify_host) {
    if (ip_literal) {
      // SSL_set1_host only matches dNSName SANs; IP endpoints must
      // verify against iPAddress SANs via the verify param
      void* param = api.SSL_get0_param(ssl);
      if (param == nullptr ||
          api.X509_VERIFY_PARAM_set1_ip_asc(param, bare.c_str()) != 1) {
        return Error(
            LastSslError(api, "X509_VERIFY_PARAM_set1_ip_asc failed"));
      }
    } else if (api.SSL_set1_host(ssl, bare.c_str()) != 1) {
      return Error(LastSslError(api, "SSL_set1_host failed"));
    }
  }
  return Error::Success;
}

void
ReadAlpn(SslApi& api, void* ssl, std::string* out)
{
  const unsigned char* proto = nullptr;
  unsigned proto_len = 0;
  api.SSL_get0_alpn_selected(ssl, &proto, &proto_len);
  if (proto != nullptr && proto_len > 0) {
    out->assign(reinterpret_cast<const char*>(proto), proto_len);
  }
}

}  // namespace

bool
TlsSession::Available(std::string* why)
{
  SslApi& api = Api();
  if (!api.ok && why != nullptr) {
    *why = api.why;
  }
  return api.ok;
}

Error
TlsSession::Handshake(
    std::unique_ptr<TlsSession>* session, int fd, const TlsOptions& opts,
    const std::string& host)
{
  SslApi& api = Api();
  if (!api.ok) {
    return Error("TLS unavailable: " + api.why);
  }
  std::unique_ptr<TlsSession> s(new TlsSession());
  Error err = BuildEngine(api, opts, host, fd, &s->ctx_, &s->ssl_);
  if (!err.IsOk()) {
    return err;
  }
  int rc = api.SSL_connect(s->ssl_);
  if (rc != 1) {
    int detail = api.SSL_get_error(s->ssl_, rc);
    if (detail == kSslErrorSyscall && errno != 0) {
      return Error(
          std::string("TLS handshake failed: ") + strerror(errno));
    }
    return Error(LastSslError(api, "TLS handshake failed"));
  }
  ReadAlpn(api, s->ssl_, &s->alpn_);
  *session = std::move(s);
  return Error::Success;
}

TlsSession::~TlsSession()
{
  SslApi& api = Api();
  if (ssl_ != nullptr && api.ok) {
    api.SSL_free(ssl_);
  }
  if (ctx_ != nullptr && api.ok) {
    api.SSL_CTX_free(ctx_);
  }
}

ssize_t
TlsSession::Send(const void* buf, size_t len)
{
  SslApi& api = Api();
  int rc = api.SSL_write(ssl_, buf, (int)len);
  if (rc > 0) {
    return rc;
  }
  int detail = api.SSL_get_error(ssl_, rc);
  if (detail == kSslErrorWantRead || detail == kSslErrorWantWrite) {
    errno = EAGAIN;  // SO_SNDTIMEO expired mid-record
  } else if (detail != kSslErrorSyscall) {
    errno = EPROTO;
  }
  return -1;
}

ssize_t
TlsSession::Recv(void* buf, size_t len)
{
  SslApi& api = Api();
  int rc = api.SSL_read(ssl_, buf, (int)len);
  if (rc > 0) {
    return rc;
  }
  int detail = api.SSL_get_error(ssl_, rc);
  if (detail == 0 /* SSL_ERROR_NONE */ ||
      detail == 6 /* SSL_ERROR_ZERO_RETURN: clean close_notify */) {
    return 0;
  }
  if (detail == kSslErrorWantRead || detail == kSslErrorWantWrite) {
    errno = EAGAIN;  // SO_RCVTIMEO expired
  } else if (detail == kSslErrorSyscall && rc == 0) {
    return 0;  // peer closed without close_notify
  } else if (detail != kSslErrorSyscall) {
    errno = EPROTO;
  }
  return -1;
}

void
TlsSession::ShutdownNotify()
{
  SslApi& api = Api();
  if (ssl_ != nullptr && api.ok) {
    api.SSL_shutdown(ssl_);
  }
}

//==============================================================================
// TlsDuplex

namespace {

int
PollFd(int fd, bool want_write, int timeout_ms)
{
  struct pollfd pfd;
  pfd.fd = fd;
  pfd.events = want_write ? POLLOUT : POLLIN;
  pfd.revents = 0;
  return poll(&pfd, 1, timeout_ms);
}

}  // namespace

Error
TlsDuplex::Handshake(
    std::unique_ptr<TlsDuplex>* duplex, int fd, const TlsOptions& opts,
    const std::string& host, int handshake_timeout_ms)
{
  SslApi& api = Api();
  if (!api.ok) {
    return Error("TLS unavailable: " + api.why);
  }
  std::unique_ptr<TlsDuplex> d(new TlsDuplex());
  d->fd_ = fd;
  Error err = BuildEngine(api, opts, host, fd, &d->ctx_, &d->ssl_);
  if (!err.IsOk()) {
    return err;
  }
  // on the SSL object, not the ctx: SSL_new copied the ctx's mode
  // before this point (SSL_set_mode is a macro over SSL_ctrl)
  api.SSL_ctrl(d->ssl_, kSslCtrlMode, kSslModeNonblockWrite, nullptr);
  int flags = fcntl(fd, F_GETFL, 0);
  if (flags < 0 || fcntl(fd, F_SETFL, flags | O_NONBLOCK) < 0) {
    return Error(
        std::string("failed to set O_NONBLOCK: ") + strerror(errno));
  }
  // non-blocking handshake bounded by the deadline
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(handshake_timeout_ms);
  while (true) {
    int rc = api.SSL_connect(d->ssl_);
    if (rc == 1) {
      break;
    }
    int detail = api.SSL_get_error(d->ssl_, rc);
    if (detail != kSslErrorWantRead && detail != kSslErrorWantWrite) {
      if (detail == kSslErrorSyscall && errno != 0) {
        return Error(
            std::string("TLS handshake failed: ") + strerror(errno));
      }
      return Error(LastSslError(api, "TLS handshake failed"));
    }
    auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
                    deadline - std::chrono::steady_clock::now())
                    .count();
    if (left <= 0) {
      return Error("TLS handshake timed out");
    }
    if (PollFd(fd, detail == kSslErrorWantWrite, (int)left) < 0 &&
        errno != EINTR) {
      return Error(std::string("poll failed: ") + strerror(errno));
    }
  }
  ReadAlpn(api, d->ssl_, &d->alpn_);
  *duplex = std::move(d);
  return Error::Success;
}

TlsDuplex::~TlsDuplex()
{
  SslApi& api = Api();
  if (ssl_ != nullptr && api.ok) {
    api.SSL_free(ssl_);
  }
  if (ctx_ != nullptr && api.ok) {
    api.SSL_CTX_free(ctx_);
  }
}

Error
TlsDuplex::SendAll(const uint8_t* data, size_t len)
{
  SslApi& api = Api();
  size_t sent = 0;
  while (sent < len) {
    int rc;
    int detail = 0;
    {
      std::lock_guard<std::mutex> lk(engine_mu_);
      rc = api.SSL_write(ssl_, data + sent, (int)(len - sent));
      if (rc <= 0) {
        detail = api.SSL_get_error(ssl_, rc);
      }
    }
    if (rc > 0) {
      sent += (size_t)rc;
      continue;
    }
    if (detail == kSslErrorWantWrite || detail == kSslErrorWantRead) {
      // socket buffer full (or engine needs peer bytes the reader will
      // pump); wait without holding the engine lock
      if (PollFd(fd_, detail == kSslErrorWantWrite, 5000) < 0 &&
          errno != EINTR) {
        return Error(std::string("poll failed: ") + strerror(errno));
      }
      continue;
    }
    if (detail == kSslErrorSyscall && errno != 0) {
      return Error(std::string("TLS send failed: ") + strerror(errno));
    }
    return Error(LastSslError(api, "TLS send failed"));
  }
  return Error::Success;
}

ssize_t
TlsDuplex::Recv(uint8_t* buf, size_t len)
{
  SslApi& api = Api();
  while (true) {
    int rc;
    int detail = 0;
    {
      std::lock_guard<std::mutex> lk(engine_mu_);
      rc = api.SSL_read(ssl_, buf, (int)len);
      if (rc <= 0) {
        detail = api.SSL_get_error(ssl_, rc);
      }
    }
    if (rc > 0) {
      return rc;
    }
    if (detail == kSslErrorWantRead || detail == kSslErrorWantWrite) {
      if (PollFd(fd_, detail == kSslErrorWantWrite, -1) < 0 &&
          errno != EINTR) {
        return -1;
      }
      continue;
    }
    if (detail == 6 /* SSL_ERROR_ZERO_RETURN */) {
      return 0;
    }
    if (detail == kSslErrorSyscall) {
      return rc == 0 ? 0 : -1;  // peer closed without close_notify
    }
    errno = EPROTO;
    return -1;
  }
}

void
TlsDuplex::ShutdownNotify()
{
  SslApi& api = Api();
  if (ssl_ != nullptr && api.ok) {
    std::lock_guard<std::mutex> lk(engine_mu_);
    api.SSL_shutdown(ssl_);
  }
}

}  // namespace tc
