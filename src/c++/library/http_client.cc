#include "http_client.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/uio.h>
#include <unistd.h>

#include <zlib.h>

#include <cstring>
#include <sstream>

namespace tc {

namespace {

std::string
UriEscape(const std::string& s)
{
  std::string out;
  for (unsigned char c : s) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out.push_back((char)c);
    } else {
      char buf[4];
      snprintf(buf, sizeof(buf), "%%%02X", c);
      out.append(buf);
    }
  }
  return out;
}

}  // namespace

//==============================================================================
// One keep-alive connection.
//
class HttpConnection {
 public:
  HttpConnection(const std::string& host, int port, const TlsOptions& tls)
      : host_(host), port_(port), tls_opts_(tls), fd_(-1)
  {
  }

  ~HttpConnection() { Close(); }

  void Close()
  {
    if (tls_ != nullptr) {
      tls_->ShutdownNotify();
      tls_.reset();
    }
    if (fd_ >= 0) {
      ::close(fd_);
      fd_ = -1;
    }
  }

  bool IsOpen() const { return fd_ >= 0; }

  Error Connect(uint64_t timeout_us)
  {
    Close();
    struct addrinfo hints, *res = nullptr;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    std::string port_str = std::to_string(port_);
    int rc = getaddrinfo(host_.c_str(), port_str.c_str(), &hints, &res);
    if (rc != 0) {
      return Error(
          "failed to resolve " + host_ + ": " + gai_strerror(rc));
    }
    Error err("failed to connect to " + host_ + ":" + port_str);
    for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
      fd_ = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
      if (fd_ < 0) {
        continue;
      }
      if (connect(fd_, ai->ai_addr, ai->ai_addrlen) == 0) {
        err = Error::Success;
        break;
      }
      ::close(fd_);
      fd_ = -1;
    }
    freeaddrinfo(res);
    if (!err.IsOk()) {
      return err;
    }
    int one = 1;
    setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    SetTimeout(timeout_us);
    if (tls_opts_.enabled) {
      Error tls_err =
          TlsSession::Handshake(&tls_, fd_, tls_opts_, host_);
      if (!tls_err.IsOk()) {
        Close();
        return tls_err;
      }
    }
    return Error::Success;
  }

  void SetTimeout(uint64_t timeout_us)
  {
    if (fd_ < 0) {
      return;
    }
    struct timeval tv;
    if (timeout_us == 0) {
      tv.tv_sec = 300;  // generous default so a dead server can't hang us
      tv.tv_usec = 0;
    } else {
      tv.tv_sec = (time_t)(timeout_us / 1000000);
      tv.tv_usec = (suseconds_t)(timeout_us % 1000000);
    }
    setsockopt(fd_, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
  }

  Error SendAll(const struct iovec* iov, int iovcnt)
  {
    // writev with continuation across partial writes; TLS sessions take
    // the per-iovec path (SSL_write has no scatter-gather)
    std::vector<struct iovec> vec(iov, iov + iovcnt);
    size_t idx = 0;
    while (idx < vec.size()) {
      ssize_t n;
      if (tls_ != nullptr) {
        n = tls_->Send(vec[idx].iov_base, vec[idx].iov_len);
      } else {
        n = writev(fd_, vec.data() + idx, (int)(vec.size() - idx));
      }
      if (n < 0) {
        if (errno == EINTR) {
          continue;
        }
        return Error(
            std::string("send failed: ") + strerror(errno));
      }
      size_t remaining = (size_t)n;
      while (idx < vec.size() && remaining >= vec[idx].iov_len) {
        remaining -= vec[idx].iov_len;
        ++idx;
      }
      if (idx < vec.size() && remaining > 0) {
        vec[idx].iov_base = (uint8_t*)vec[idx].iov_base + remaining;
        vec[idx].iov_len -= remaining;
      }
    }
    return Error::Success;
  }

  // recv() for whichever transport is live (plain fd or TLS session)
  ssize_t RecvSome(void* buf, size_t len)
  {
    if (tls_ != nullptr) {
      return tls_->Recv(buf, len);
    }
    return recv(fd_, buf, len, 0);
  }

  // Read an HTTP/1.1 response: status code, headers, body (Content-Length
  // or chunked).
  Error ReadResponse(
      long* code, std::map<std::string, std::string>* headers,
      std::string* body, bool* got_bytes = nullptr)
  {
    if (got_bytes != nullptr) {
      *got_bytes = false;
    }
    std::string buf;
    size_t header_end;
    while (true) {
      header_end = buf.find("\r\n\r\n");
      if (header_end != std::string::npos) {
        break;
      }
      char tmp[8192];
      ssize_t n = RecvSome(tmp, sizeof(tmp));
      if (n <= 0) {
        Close();
        return Error(
            n == 0 ? "connection closed while reading response headers"
                   : std::string("recv failed: ") + strerror(errno));
      }
      if (got_bytes != nullptr) {
        *got_bytes = true;
      }
      buf.append(tmp, (size_t)n);
    }
    // status line
    size_t line_end = buf.find("\r\n");
    std::string status_line = buf.substr(0, line_end);
    size_t sp = status_line.find(' ');
    if (sp == std::string::npos) {
      Close();
      return Error("malformed HTTP status line: " + status_line);
    }
    *code = strtol(status_line.c_str() + sp + 1, nullptr, 10);
    // headers
    headers->clear();
    size_t pos = line_end + 2;
    while (pos < header_end) {
      size_t eol = buf.find("\r\n", pos);
      std::string line = buf.substr(pos, eol - pos);
      pos = eol + 2;
      size_t colon = line.find(':');
      if (colon == std::string::npos) {
        continue;
      }
      std::string key = line.substr(0, colon);
      for (auto& c : key) {
        c = (char)tolower((unsigned char)c);
      }
      size_t vstart = colon + 1;
      while (vstart < line.size() && line[vstart] == ' ') {
        ++vstart;
      }
      (*headers)[key] = line.substr(vstart);
    }
    std::string rest = buf.substr(header_end + 4);
    // body
    auto te = headers->find("transfer-encoding");
    if (te != headers->end() && te->second.find("chunked") !=
        std::string::npos) {
      return ReadChunked(rest, body);
    }
    size_t content_length = 0;
    auto cl = headers->find("content-length");
    if (cl != headers->end()) {
      content_length = (size_t)strtoull(cl->second.c_str(), nullptr, 10);
    }
    body->assign(rest);
    while (body->size() < content_length) {
      char tmp[65536];
      size_t want = content_length - body->size();
      ssize_t n = RecvSome(
          tmp, want < sizeof(tmp) ? want : sizeof(tmp));
      if (n <= 0) {
        Close();
        return Error(
            n == 0 ? "connection closed while reading response body"
                   : std::string("recv failed: ") + strerror(errno));
      }
      body->append(tmp, (size_t)n);
    }
    return Error::Success;
  }

 private:
  Error ReadChunked(const std::string& initial, std::string* body)
  {
    std::string buf = initial;
    body->clear();
    size_t pos = 0;
    while (true) {
      // ensure a full chunk-size line
      size_t eol;
      while ((eol = buf.find("\r\n", pos)) == std::string::npos) {
        char tmp[8192];
        ssize_t n = RecvSome(tmp, sizeof(tmp));
        if (n <= 0) {
          Close();
          return Error("connection closed mid chunked body");
        }
        buf.append(tmp, (size_t)n);
      }
      size_t chunk_len =
          (size_t)strtoull(buf.c_str() + pos, nullptr, 16);
      pos = eol + 2;
      if (chunk_len == 0) {
        // consume the (possibly empty) trailer section up to its blank
        // line so the keep-alive connection stays framed
        while (true) {
          size_t teol;
          while ((teol = buf.find("\r\n", pos)) == std::string::npos) {
            char tmp[1024];
            ssize_t n = RecvSome(tmp, sizeof(tmp));
            if (n <= 0) {
              Close();
              return Error("connection closed in chunked trailer");
            }
            buf.append(tmp, (size_t)n);
          }
          bool blank = (teol == pos);
          pos = teol + 2;
          if (blank) {
            return Error::Success;
          }
        }
      }
      while (buf.size() < pos + chunk_len + 2) {
        char tmp[65536];
        ssize_t n = RecvSome(tmp, sizeof(tmp));
        if (n <= 0) {
          Close();
          return Error("connection closed mid chunked body");
        }
        buf.append(tmp, (size_t)n);
      }
      body->append(buf, pos, chunk_len);
      pos += chunk_len + 2;  // skip trailing CRLF
    }
  }

  std::string host_;
  int port_;
  TlsOptions tls_opts_;
  std::unique_ptr<TlsSession> tls_;
  int fd_;
};

//==============================================================================
// Keep-alive connection pool.
//
class HttpConnectionPool {
 public:
  HttpConnectionPool(
      const std::string& host, int port,
      const TlsOptions& tls = TlsOptions())
      : host_(host), port_(port), tls_(tls)
  {
  }

  std::unique_ptr<HttpConnection> Acquire()
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!idle_.empty()) {
      auto conn = std::move(idle_.back());
      idle_.pop_back();
      return conn;
    }
    return std::unique_ptr<HttpConnection>(
        new HttpConnection(host_, port_, tls_));
  }

  void Release(std::unique_ptr<HttpConnection> conn)
  {
    if (conn && conn->IsOpen()) {
      std::lock_guard<std::mutex> lk(mu_);
      idle_.push_back(std::move(conn));
    }
  }

 private:
  std::string host_;
  int port_;
  TlsOptions tls_;
  std::mutex mu_;
  std::vector<std::unique_ptr<HttpConnection>> idle_;
};

//==============================================================================
// HTTP result implementation.
//
class InferResultHttp : public InferResult {
 public:
  static Error Create(
      InferResult** result, std::shared_ptr<std::string> body,
      size_t header_length);

  Error ModelName(std::string* name) const override
  {
    return GetString("model_name", name);
  }
  Error ModelVersion(std::string* version) const override
  {
    return GetString("model_version", version);
  }
  Error Id(std::string* id) const override { return GetString("id", id); }

  Error Shape(
      const std::string& output_name,
      std::vector<int64_t>* shape) const override
  {
    auto out = FindOutput(output_name);
    if (out == nullptr) {
      return Error("output '" + output_name + "' not found");
    }
    shape->clear();
    auto shape_val = out->Get("shape");
    if (shape_val != nullptr) {
      for (const auto& d : shape_val->Elements()) {
        shape->push_back(d->AsInt());
      }
    }
    return Error::Success;
  }

  Error Datatype(
      const std::string& output_name, std::string* datatype) const override
  {
    auto out = FindOutput(output_name);
    if (out == nullptr) {
      return Error("output '" + output_name + "' not found");
    }
    auto dt = out->Get("datatype");
    *datatype = dt ? dt->AsString() : "";
    return Error::Success;
  }

  Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const override
  {
    auto it = raw_outputs_.find(output_name);
    if (it == raw_outputs_.end()) {
      return Error(
          "output '" + output_name + "' has no binary data");
    }
    *buf = it->second.first;
    *byte_size = it->second.second;
    return Error::Success;
  }

  Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const override
  {
    const uint8_t* buf;
    size_t byte_size;
    Error err = RawData(output_name, &buf, &byte_size);
    if (!err.IsOk()) {
      return err;
    }
    string_result->clear();
    size_t pos = 0;
    while (pos + 4 <= byte_size) {
      uint32_t len;
      memcpy(&len, buf + pos, 4);
      pos += 4;
      if (pos + len > byte_size) {
        return Error("malformed BYTES tensor in output " + output_name);
      }
      string_result->emplace_back(
          reinterpret_cast<const char*>(buf + pos), len);
      pos += len;
    }
    return Error::Success;
  }

  std::string DebugString() const override
  {
    return header_ ? header_->Serialize() : "{}";
  }

  Error RequestStatus() const override { return status_; }

 private:
  Error GetString(const char* key, std::string* out) const
  {
    auto v = header_ ? header_->Get(key) : nullptr;
    *out = v ? v->AsString() : "";
    return Error::Success;
  }

  json::ValuePtr FindOutput(const std::string& name) const
  {
    auto outputs = header_ ? header_->Get("outputs") : nullptr;
    if (outputs == nullptr) {
      return nullptr;
    }
    for (const auto& out : outputs->Elements()) {
      auto n = out->Get("name");
      if (n != nullptr && n->AsString() == name) {
        return out;
      }
    }
    return nullptr;
  }

  std::shared_ptr<std::string> body_;
  json::ValuePtr header_;
  Error status_;
  // name -> (ptr into body_, len)
  std::map<std::string, std::pair<const uint8_t*, size_t>> raw_outputs_;
};

Error
InferResultHttp::Create(
    InferResult** result, std::shared_ptr<std::string> body,
    size_t header_length)
{
  auto* res = new InferResultHttp();
  res->body_ = body;
  size_t json_len = header_length ? header_length : body->size();
  std::string err_str;
  res->header_ = json::Parse(body->substr(0, json_len), &err_str);
  if (res->header_ == nullptr) {
    delete res;
    return Error("failed to parse inference response JSON: " + err_str);
  }
  if (res->header_->Has("error")) {
    res->status_ = Error(res->header_->Get("error")->AsString());
  }
  // map binary sections: outputs in order, each with binary_data_size param
  size_t offset = json_len;
  auto outputs = res->header_->Get("outputs");
  if (outputs != nullptr) {
    for (const auto& out : outputs->Elements()) {
      auto params = out->Get("parameters");
      auto name = out->Get("name");
      if (params != nullptr && params->Has("binary_data_size") &&
          name != nullptr) {
        size_t size = (size_t)params->Get("binary_data_size")->AsInt();
        if (offset + size > body->size()) {
          delete res;
          return Error("binary output section exceeds response body");
        }
        res->raw_outputs_[name->AsString()] = {
            reinterpret_cast<const uint8_t*>(body->data()) + offset, size};
        offset += size;
      }
    }
  }
  *result = res;
  return Error::Success;
}

//==============================================================================

Error
InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const std::string& server_url, bool verbose, int concurrency,
    const HttpSslOptions& ssl_options)
{
  if (server_url.rfind("https://", 0) == 0) {
    std::string why;
    if (!TlsSession::Available(&why)) {
      return Error("https requested but " + why);
    }
  }
  // the transport loads certificates with the *_PEM loaders only; a
  // DER request must fail here rather than be silently parsed as PEM
  if (ssl_options.cert_type != HttpSslOptions::CERTTYPE::CERT_PEM) {
    return Error("unsupported ssl certificate type: only PEM is supported");
  }
  if (ssl_options.key_type != HttpSslOptions::KEYTYPE::KEY_PEM) {
    return Error("unsupported ssl key type: only PEM is supported");
  }
  client->reset(new InferenceServerHttpClient(
      server_url, verbose, concurrency, ssl_options));
  return Error::Success;
}

InferenceServerHttpClient::InferenceServerHttpClient(
    const std::string& url, bool verbose, int concurrency,
    const HttpSslOptions& ssl_options)
    : InferenceServerClient(verbose)
{
  // TLS iff the URL carries the https scheme (reference semantics:
  // SetSSLCurlOptions applies to an https:// URL,
  // reference http_client.cc:253-280)
  bool use_tls = url.rfind("https://", 0) == 0;
  std::string stripped = url;
  auto scheme = stripped.find("://");
  if (scheme != std::string::npos) {
    stripped = stripped.substr(scheme + 3);
  }
  auto colon = stripped.rfind(':');
  if (colon == std::string::npos) {
    host_ = stripped;
    port_ = use_tls ? 443 : 8000;
  } else {
    host_ = stripped.substr(0, colon);
    port_ = atoi(stripped.c_str() + colon + 1);
  }
  TlsOptions tls;
  tls.enabled = use_tls;
  tls.ca_file = ssl_options.ca_info;
  tls.cert_file = ssl_options.cert;
  tls.key_file = ssl_options.key;
  tls.verify_peer = ssl_options.verify_peer != 0;
  tls.verify_host = ssl_options.verify_host != 0;
  pool_.reset(new HttpConnectionPool(host_, port_, tls));
  for (int i = 0; i < concurrency; ++i) {
    workers_.emplace_back(&InferenceServerHttpClient::AsyncWorker, this);
  }
}

InferenceServerHttpClient::~InferenceServerHttpClient()
{
  {
    std::lock_guard<std::mutex> lk(async_mu_);
    exiting_ = true;
  }
  async_cv_.notify_all();
  for (auto& w : workers_) {
    w.join();
  }
}

void
InferenceServerHttpClient::AsyncWorker()
{
  while (true) {
    std::function<void()> job;
    {
      std::unique_lock<std::mutex> lk(async_mu_);
      async_cv_.wait(
          lk, [this] { return exiting_ || !async_queue_.empty(); });
      if (exiting_ && async_queue_.empty()) {
        return;
      }
      job = std::move(async_queue_.front());
      async_queue_.pop_front();
    }
    job();
  }
}

//==============================================================================
// plumbing

Error
InferenceServerHttpClient::Get(
    const std::string& path, long* http_code, std::string* response)
{
  auto conn = pool_->Acquire();
  Error err;
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (!conn->IsOpen()) {
      err = conn->Connect(0);
      if (!err.IsOk()) {
        return err;
      }
    }
    std::ostringstream req;
    req << "GET " << path << " HTTP/1.1\r\nHost: " << host_
        << "\r\nConnection: keep-alive\r\n\r\n";
    std::string header = req.str();
    struct iovec iov{(void*)header.data(), header.size()};
    err = conn->SendAll(&iov, 1);
    if (!err.IsOk()) {
      conn->Close();
      continue;  // stale keep-alive connection: retry once fresh
    }
    std::map<std::string, std::string> headers;
    err = conn->ReadResponse(http_code, &headers, response);
    if (err.IsOk()) {
      break;
    }
    conn->Close();
  }
  if (verbose_ && err.IsOk()) {
    printf("GET %s -> %ld\n%s\n", path.c_str(), *http_code,
           response->c_str());
  }
  pool_->Release(std::move(conn));
  return err;
}

Error
InferenceServerHttpClient::Post(
    const std::string& path, const std::string& body, long* http_code,
    std::string* response,
    const std::map<std::string, std::string>& extra_headers)
{
  auto conn = pool_->Acquire();
  Error err;
  for (int attempt = 0; attempt < 2; ++attempt) {
    if (!conn->IsOpen()) {
      err = conn->Connect(0);
      if (!err.IsOk()) {
        return err;
      }
    }
    std::ostringstream req;
    req << "POST " << path << " HTTP/1.1\r\nHost: " << host_
        << "\r\nConnection: keep-alive\r\nContent-Type: application/json"
        << "\r\nContent-Length: " << body.size() << "\r\n";
    for (const auto& kv : extra_headers) {
      req << kv.first << ": " << kv.second << "\r\n";
    }
    req << "\r\n";
    std::string header = req.str();
    struct iovec iov[2] = {
        {(void*)header.data(), header.size()},
        {(void*)body.data(), body.size()},
    };
    err = conn->SendAll(iov, body.empty() ? 1 : 2);
    if (!err.IsOk()) {
      conn->Close();
      continue;
    }
    std::map<std::string, std::string> headers;
    err = conn->ReadResponse(http_code, &headers, response);
    if (err.IsOk()) {
      break;
    }
    conn->Close();
  }
  if (verbose_ && err.IsOk()) {
    printf("POST %s -> %ld\n%s\n", path.c_str(), *http_code,
           response->c_str());
  }
  pool_->Release(std::move(conn));
  return err;
}


namespace {

// zlib-backed body (de)compression for the gzip/deflate content codings
// (role of reference http_client.cc:563-580 CompressInput, which rides
// libcurl; windowBits 15+16 selects the gzip wrapper).
Error
CompressBuffer(
    const std::string& algorithm, const std::vector<uint8_t>& in,
    std::vector<uint8_t>* out)
{
  z_stream strm{};
  int window_bits = (algorithm == "gzip") ? 15 + 16 : 15;
  if (deflateInit2(
          &strm, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window_bits, 8,
          Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("unable to initialize " + algorithm + " compression");
  }
  out->resize(deflateBound(&strm, in.size()));
  // feed in <4 GiB chunks: zlib's avail_in/avail_out are 32-bit
  const size_t kChunk = 1u << 30;
  size_t consumed = 0;
  size_t produced = 0;
  int rc = Z_OK;
  do {
    size_t in_chunk = std::min(kChunk, in.size() - consumed);
    strm.next_in = (Bytef*)in.data() + consumed;
    strm.avail_in = (uInt)in_chunk;
    bool last = (consumed + in_chunk == in.size());
    do {
      size_t out_chunk = std::min(kChunk, out->size() - produced);
      strm.next_out = out->data() + produced;
      strm.avail_out = (uInt)out_chunk;
      rc = deflate(&strm, last ? Z_FINISH : Z_NO_FLUSH);
      produced += out_chunk - strm.avail_out;
    } while (rc == Z_OK && strm.avail_in > 0);
    consumed += in_chunk - strm.avail_in;
  } while (rc == Z_OK && consumed < in.size());
  deflateEnd(&strm);
  if (rc != Z_STREAM_END) {
    return Error(algorithm + " compression failed");
  }
  out->resize(produced);
  return Error::Success;
}

Error
DecompressString(const std::string& encoding, std::string* body)
{
  z_stream strm{};
  // 15+32: auto-detect gzip or zlib wrapper
  if (inflateInit2(&strm, 15 + 32) != Z_OK) {
    return Error("unable to initialize " + encoding + " decompression");
  }
  std::string out;
  out.resize(body->size() * 4 + 1024);
  const size_t kChunk = 1u << 30;  // zlib counters are 32-bit
  size_t consumed = 0;
  size_t written = 0;
  int rc = Z_OK;
  do {
    size_t in_chunk = std::min(kChunk, body->size() - consumed);
    strm.next_in = (Bytef*)body->data() + consumed;
    strm.avail_in = (uInt)in_chunk;
    do {
      if (written == out.size()) {
        out.resize(out.size() * 2);
      }
      size_t out_chunk = std::min(kChunk, out.size() - written);
      strm.next_out = (Bytef*)out.data() + written;
      strm.avail_out = (uInt)out_chunk;
      rc = inflate(&strm, Z_NO_FLUSH);
      written += out_chunk - strm.avail_out;
    } while (rc == Z_OK && strm.avail_in > 0);
    consumed += in_chunk - strm.avail_in;
  } while (rc == Z_OK && consumed < body->size());
  inflateEnd(&strm);
  if (rc != Z_STREAM_END) {
    return Error(encoding + " decompression failed");
  }
  out.resize(written);
  *body = std::move(out);
  return Error::Success;
}

}  // namespace

Error
InferenceServerHttpClient::PostBinary(
    const std::string& path, const std::vector<uint8_t>& body,
    size_t header_length, long* http_code, std::string* response,
    size_t* response_header_length, uint64_t timeout_us,
    const std::string& extra_headers, std::string* response_content_encoding)
{
  auto conn = pool_->Acquire();
  Error err;
  std::map<std::string, std::string> resp_headers;
  for (int attempt = 0; attempt < 2; ++attempt) {
    bool reused = conn->IsOpen();
    if (!reused) {
      err = conn->Connect(timeout_us);
      if (!err.IsOk()) {
        return err;
      }
    } else {
      conn->SetTimeout(timeout_us);
    }
    std::ostringstream req;
    req << "POST " << path << " HTTP/1.1\r\nHost: " << host_
        << "\r\nConnection: keep-alive"
        << "\r\nContent-Type: application/octet-stream"
        << "\r\nInference-Header-Content-Length: " << header_length
        << extra_headers
        << "\r\nContent-Length: " << body.size() << "\r\n\r\n";
    std::string header = req.str();
    struct iovec iov[2] = {
        {(void*)header.data(), header.size()},
        {(void*)body.data(), body.size()},
    };
    err = conn->SendAll(iov, 2);
    if (!err.IsOk()) {
      conn->Close();
      if (reused) {
        continue;  // stale keep-alive connection detected at send
      }
      break;
    }
    bool got_bytes = false;
    err = conn->ReadResponse(http_code, &resp_headers, response,
                             &got_bytes);
    if (err.IsOk()) {
      break;
    }
    conn->Close();
    // Inference POSTs are not idempotent (sequences, KV-cache state):
    // only resend when a reused connection died before delivering ANY
    // response bytes — the classic stale keep-alive race, where the
    // server closed before our request arrived.
    if (!(reused && !got_bytes)) {
      break;
    }
  }
  if (err.IsOk()) {
    auto it = resp_headers.find("inference-header-content-length");
    *response_header_length =
        it == resp_headers.end()
            ? 0
            : (size_t)strtoull(it->second.c_str(), nullptr, 10);
    if (response_content_encoding != nullptr) {
      auto enc = resp_headers.find("content-encoding");
      *response_content_encoding =
          enc == resp_headers.end() ? "" : enc->second;
    }
  }
  pool_->Release(std::move(conn));
  return err;
}

namespace {

Error
CheckJsonResponse(long code, const std::string& body)
{
  if (code >= 400) {
    std::string err_str;
    auto doc = json::Parse(body, &err_str);
    if (doc != nullptr && doc->Has("error")) {
      return Error(doc->Get("error")->AsString());
    }
    return Error("HTTP " + std::to_string(code) + ": " + body);
  }
  return Error::Success;
}

}  // namespace

//==============================================================================
// API surface

Error
InferenceServerHttpClient::IsServerLive(bool* live)
{
  long code;
  std::string body;
  Error err = Get("/v2/health/live", &code, &body);
  *live = err.IsOk() && code == 200;
  return err;
}

Error
InferenceServerHttpClient::IsServerReady(bool* ready)
{
  long code;
  std::string body;
  Error err = Get("/v2/health/ready", &code, &body);
  *ready = err.IsOk() && code == 200;
  return err;
}

Error
InferenceServerHttpClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version)
{
  std::string path = "/v2/models/" + UriEscape(model_name);
  if (!model_version.empty()) {
    path += "/versions/" + model_version;
  }
  long code;
  std::string body;
  Error err = Get(path + "/ready", &code, &body);
  *ready = err.IsOk() && code == 200;
  return err;
}

Error
InferenceServerHttpClient::ServerMetadata(std::string* server_metadata)
{
  long code;
  Error err = Get("/v2", &code, server_metadata);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *server_metadata);
}

Error
InferenceServerHttpClient::ModelMetadata(
    std::string* model_metadata, const std::string& model_name,
    const std::string& model_version)
{
  std::string path = "/v2/models/" + UriEscape(model_name);
  if (!model_version.empty()) {
    path += "/versions/" + model_version;
  }
  long code;
  Error err = Get(path, &code, model_metadata);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *model_metadata);
}

Error
InferenceServerHttpClient::ModelConfig(
    std::string* model_config, const std::string& model_name,
    const std::string& model_version)
{
  std::string path = "/v2/models/" + UriEscape(model_name);
  if (!model_version.empty()) {
    path += "/versions/" + model_version;
  }
  long code;
  Error err = Get(path + "/config", &code, model_config);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *model_config);
}

Error
InferenceServerHttpClient::ModelRepositoryIndex(std::string* repository_index)
{
  long code;
  Error err = Post("/v2/repository/index", "", &code, repository_index);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *repository_index);
}

Error
InferenceServerHttpClient::LoadModel(
    const std::string& model_name, const std::string& config)
{
  std::string body;
  if (!config.empty()) {
    auto doc = json::Value::MakeObject();
    auto params = json::Value::MakeObject();
    params->Set("config", config);
    doc->Set("parameters", params);
    body = doc->Serialize();
  }
  long code;
  std::string response;
  Error err = Post(
      "/v2/repository/models/" + UriEscape(model_name) + "/load", body,
      &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::UnloadModel(const std::string& model_name)
{
  long code;
  std::string response;
  Error err = Post(
      "/v2/repository/models/" + UriEscape(model_name) + "/unload", "",
      &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::ModelInferenceStatistics(
    std::string* infer_stat, const std::string& model_name,
    const std::string& model_version)
{
  std::string path = "/v2/models/stats";
  if (!model_name.empty()) {
    path = "/v2/models/" + UriEscape(model_name);
    if (!model_version.empty()) {
      path += "/versions/" + model_version;
    }
    path += "/stats";
  }
  long code;
  Error err = Get(path, &code, infer_stat);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *infer_stat);
}

Error
InferenceServerHttpClient::UpdateTraceSettings(
    std::string* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings)
{
  auto doc = json::Value::MakeObject();
  for (const auto& kv : settings) {
    if (kv.second.size() == 1) {
      doc->Set(kv.first, kv.second[0]);
    } else {
      auto arr = json::Value::MakeArray();
      for (const auto& v : kv.second) {
        arr->Append(std::make_shared<json::Value>(v));
      }
      doc->Set(kv.first, arr);
    }
  }
  std::string path = model_name.empty()
                         ? "/v2/trace/setting"
                         : "/v2/models/" + UriEscape(model_name) +
                               "/trace/setting";
  long code;
  Error err = Post(path, doc->Serialize(), &code, response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *response);
}

Error
InferenceServerHttpClient::GetTraceSettings(
    std::string* settings, const std::string& model_name)
{
  std::string path = model_name.empty()
                         ? "/v2/trace/setting"
                         : "/v2/models/" + UriEscape(model_name) +
                               "/trace/setting";
  long code;
  Error err = Get(path, &code, settings);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *settings);
}

Error
InferenceServerHttpClient::UpdateLogSettings(
    std::string* response, const std::string& settings_json)
{
  long code;
  Error err = Post("/v2/logging", settings_json, &code, response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *response);
}

Error
InferenceServerHttpClient::GetLogSettings(std::string* settings)
{
  long code;
  Error err = Get("/v2/logging", &code, settings);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *settings);
}

Error
InferenceServerHttpClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset)
{
  auto doc = json::Value::MakeObject();
  doc->Set("key", key);
  doc->Set("offset", (int64_t)offset);
  doc->Set("byte_size", (int64_t)byte_size);
  long code;
  std::string response;
  Error err = Post(
      "/v2/systemsharedmemory/region/" + UriEscape(name) + "/register",
      doc->Serialize(), &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::UnregisterSystemSharedMemory(
    const std::string& name)
{
  std::string path = name.empty()
                         ? "/v2/systemsharedmemory/unregister"
                         : "/v2/systemsharedmemory/region/" +
                               UriEscape(name) + "/unregister";
  long code;
  std::string response;
  Error err = Post(path, "", &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::SystemSharedMemoryStatus(std::string* status)
{
  long code;
  Error err = Get("/v2/systemsharedmemory/status", &code, status);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *status);
}

Error
InferenceServerHttpClient::RegisterXlaSharedMemory(
    const std::string& name, const std::string& raw_handle,
    size_t byte_size, int device_ordinal)
{
  auto doc = json::Value::MakeObject();
  auto handle = json::Value::MakeObject();
  handle->Set("b64", raw_handle);
  doc->Set("raw_handle", handle);
  doc->Set("device_ordinal", (int64_t)device_ordinal);
  doc->Set("byte_size", (int64_t)byte_size);
  long code;
  std::string response;
  Error err = Post(
      "/v2/xlasharedmemory/region/" + UriEscape(name) + "/register",
      doc->Serialize(), &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::UnregisterXlaSharedMemory(const std::string& name)
{
  std::string path = name.empty()
                         ? "/v2/xlasharedmemory/unregister"
                         : "/v2/xlasharedmemory/region/" + UriEscape(name) +
                               "/unregister";
  long code;
  std::string response;
  Error err = Post(path, "", &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::XlaSharedMemoryStatus(std::string* status)
{
  long code;
  Error err = Get("/v2/xlasharedmemory/status", &code, status);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *status);
}

Error
InferenceServerHttpClient::RegisterCudaSharedMemory(
    const std::string& name, const std::string& raw_handle,
    size_t byte_size, int device_id)
{
  auto doc = json::Value::MakeObject();
  auto handle = json::Value::MakeObject();
  handle->Set("b64", raw_handle);
  doc->Set("raw_handle", handle);
  doc->Set("device_id", (int64_t)device_id);
  doc->Set("byte_size", (int64_t)byte_size);
  long code;
  std::string response;
  Error err = Post(
      "/v2/cudasharedmemory/region/" + UriEscape(name) + "/register",
      doc->Serialize(), &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::UnregisterCudaSharedMemory(
    const std::string& name)
{
  std::string path = name.empty()
                         ? "/v2/cudasharedmemory/unregister"
                         : "/v2/cudasharedmemory/region/" +
                               UriEscape(name) + "/unregister";
  long code;
  std::string response;
  Error err = Post(path, "", &code, &response);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, response);
}

Error
InferenceServerHttpClient::CudaSharedMemoryStatus(std::string* status)
{
  long code;
  Error err = Get("/v2/cudasharedmemory/status", &code, status);
  if (!err.IsOk()) {
    return err;
  }
  return CheckJsonResponse(code, *status);
}

//==============================================================================
// inference

Error
InferenceServerHttpClient::GenerateRequestBody(
    std::vector<uint8_t>* request_body, size_t* header_length,
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  auto doc = json::Value::MakeObject();
  if (!options.request_id_.empty()) {
    doc->Set("id", options.request_id_);
  }
  auto params = json::Value::MakeObject();
  if (options.sequence_id_ != 0) {
    params->Set("sequence_id", (int64_t)options.sequence_id_);
    params->Set("sequence_start", options.sequence_start_);
    params->Set("sequence_end", options.sequence_end_);
  }
  if (options.priority_ != 0) {
    params->Set("priority", (int64_t)options.priority_);
  }
  if (options.server_timeout_us_ != 0) {
    params->Set("timeout", (int64_t)options.server_timeout_us_);
  }
  if (!params->Members().empty()) {
    doc->Set("parameters", params);
  }

  auto inputs_arr = json::Value::MakeArray();
  size_t total_binary = 0;
  for (auto* input : inputs) {
    auto in = json::Value::MakeObject();
    in->Set("name", input->Name());
    in->Set("datatype", input->Datatype());
    auto shape = json::Value::MakeArray();
    for (auto d : input->Shape()) {
      shape->Append(std::make_shared<json::Value>((int64_t)d));
    }
    in->Set("shape", shape);
    auto in_params = json::Value::MakeObject();
    if (input->IsSharedMemory()) {
      in_params->Set("shared_memory_region", input->SharedMemoryName());
      in_params->Set(
          "shared_memory_byte_size",
          (int64_t)input->SharedMemoryByteSize());
      if (input->SharedMemoryOffset() != 0) {
        in_params->Set(
            "shared_memory_offset", (int64_t)input->SharedMemoryOffset());
      }
    } else {
      in_params->Set(
          "binary_data_size", (int64_t)input->TotalByteSize());
      total_binary += input->TotalByteSize();
    }
    in->Set("parameters", in_params);
    inputs_arr->Append(in);
  }
  doc->Set("inputs", inputs_arr);

  if (!outputs.empty()) {
    auto outputs_arr = json::Value::MakeArray();
    for (const auto* output : outputs) {
      auto out = json::Value::MakeObject();
      out->Set("name", output->Name());
      auto out_params = json::Value::MakeObject();
      if (output->IsSharedMemory()) {
        out_params->Set(
            "shared_memory_region", output->SharedMemoryName());
        out_params->Set(
            "shared_memory_byte_size",
            (int64_t)output->SharedMemoryByteSize());
        if (output->SharedMemoryOffset() != 0) {
          out_params->Set(
              "shared_memory_offset",
              (int64_t)output->SharedMemoryOffset());
        }
      } else {
        out_params->Set("binary_data", output->BinaryData());
        if (output->ClassCount() != 0) {
          out_params->Set(
              "classification", (int64_t)output->ClassCount());
        }
      }
      out->Set("parameters", out_params);
      outputs_arr->Append(out);
    }
    doc->Set("outputs", outputs_arr);
  }

  std::string header = doc->Serialize();
  *header_length = header.size();
  request_body->clear();
  request_body->reserve(header.size() + total_binary);
  request_body->insert(request_body->end(), header.begin(), header.end());
  for (auto* input : inputs) {
    if (input->IsSharedMemory()) {
      continue;
    }
    input->PrepareForRequest();
    const uint8_t* buf;
    size_t len;
    bool end_of_input = false;
    while (!end_of_input) {
      input->GetNext(&buf, &len, &end_of_input);
      if (buf != nullptr && len > 0) {
        request_body->insert(request_body->end(), buf, buf + len);
      }
    }
  }
  return Error::Success;
}

Error
InferenceServerHttpClient::ParseResponseBody(
    InferResult** result, const std::vector<uint8_t>& response_body,
    size_t header_length)
{
  auto shared = std::make_shared<std::string>(
      reinterpret_cast<const char*>(response_body.data()),
      response_body.size());
  return InferResultHttp::Create(result, shared, header_length);
}

Error
InferenceServerHttpClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const std::string& request_compression_algorithm,
    const std::string& response_compression_algorithm)
{
  RequestTimers timer;
  timer.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);

  std::vector<uint8_t> body;
  size_t header_length;
  Error err = GenerateRequestBody(
      &body, &header_length, options, inputs, outputs);
  if (!err.IsOk()) {
    return err;
  }

  std::string extra_headers;
  if (!request_compression_algorithm.empty()) {
    if (request_compression_algorithm != "gzip" &&
        request_compression_algorithm != "deflate") {
      return Error(
          "unsupported request compression algorithm: " +
          request_compression_algorithm);
    }
    std::vector<uint8_t> compressed;
    err = CompressBuffer(request_compression_algorithm, body, &compressed);
    if (!err.IsOk()) {
      return err;
    }
    body = std::move(compressed);
    extra_headers +=
        "\r\nContent-Encoding: " + request_compression_algorithm;
  }
  if (!response_compression_algorithm.empty()) {
    if (response_compression_algorithm != "gzip" &&
        response_compression_algorithm != "deflate") {
      return Error(
          "unsupported response compression algorithm: " +
          response_compression_algorithm);
    }
    extra_headers +=
        "\r\nAccept-Encoding: " + response_compression_algorithm;
  }

  std::string path = "/v2/models/" + UriEscape(options.model_name_);
  if (!options.model_version_.empty()) {
    path += "/versions/" + options.model_version_;
  }
  path += "/infer";

  timer.CaptureTimestamp(RequestTimers::Kind::SEND_START);
  long code;
  std::string response;
  size_t response_header_length;
  std::string response_encoding;
  err = PostBinary(
      path, body, header_length, &code, &response,
      &response_header_length, options.client_timeout_us_,
      extra_headers, &response_encoding);
  timer.CaptureTimestamp(RequestTimers::Kind::SEND_END);
  if (!err.IsOk()) {
    return err;
  }
  if (!response_encoding.empty()) {
    err = DecompressString(response_encoding, &response);
    if (!err.IsOk()) {
      return err;
    }
  }

  timer.CaptureTimestamp(RequestTimers::Kind::RECV_START);
  // move, don't copy: for big tensor responses this is the hot path
  auto shared = std::make_shared<std::string>(std::move(response));
  err = InferResultHttp::Create(result, shared, response_header_length);
  timer.CaptureTimestamp(RequestTimers::Kind::RECV_END);
  timer.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
  if (!err.IsOk()) {
    return err;
  }
  if (code >= 400 && (*result)->RequestStatus().IsOk()) {
    delete *result;
    *result = nullptr;
    return Error("HTTP " + std::to_string(code) + ": " + *shared);
  }
  UpdateInferStat(timer);
  if (verbose_) {
    printf("infer %s -> %s\n", options.model_name_.c_str(),
           (*result)->DebugString().c_str());
  }
  return Error::Success;
}

Error
InferenceServerHttpClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const std::string& request_compression_algorithm,
    const std::string& response_compression_algorithm)
{
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  // Inputs reference user buffers; per the API contract (same as the
  // reference) the caller must keep them alive until the callback fires.
  InferOptions opts = options;
  std::vector<InferInput*> ins = inputs;
  std::vector<const InferRequestedOutput*> outs = outputs;
  std::string req_comp = request_compression_algorithm;
  std::string resp_comp = response_compression_algorithm;
  {
    std::lock_guard<std::mutex> lk(async_mu_);
    if (exiting_) {
      return Error("client is shutting down");
    }
    async_queue_.emplace_back([this, callback, opts, ins, outs, req_comp,
                               resp_comp] {
      InferResult* result = nullptr;
      Error err = Infer(&result, opts, ins, outs, req_comp, resp_comp);
      if (!err.IsOk() && result == nullptr) {
        // surface transport failure through a result-less sentinel: the
        // reference delivers a result whose RequestStatus is the error
        class ErrorResult : public InferResult {
         public:
          explicit ErrorResult(const Error& e) : err_(e) {}
          Error ModelName(std::string* v) const override
          {
            v->clear();
            return err_;
          }
          Error ModelVersion(std::string* v) const override
          {
            v->clear();
            return err_;
          }
          Error Id(std::string* v) const override
          {
            v->clear();
            return err_;
          }
          Error Shape(const std::string&, std::vector<int64_t>* s)
              const override
          {
            s->clear();
            return err_;
          }
          Error Datatype(const std::string&, std::string* d) const override
          {
            d->clear();
            return err_;
          }
          Error RawData(const std::string&, const uint8_t** b, size_t* n)
              const override
          {
            *b = nullptr;
            *n = 0;
            return err_;
          }
          Error StringData(const std::string&, std::vector<std::string>* r)
              const override
          {
            r->clear();
            return err_;
          }
          std::string DebugString() const override
          {
            return err_.Message();
          }
          Error RequestStatus() const override { return err_; }

         private:
          Error err_;
        };
        result = new ErrorResult(err);
      }
      callback(result);
    });
  }
  async_cv_.notify_one();
  return Error::Success;
}

}  // namespace tc
