// HTTP/REST client for the KServe-v2 protocol with the binary-tensor
// extension.
//
// Re-design of the reference InferenceServerHttpClient
// (reference src/c++/library/http_client.h:106-650).  The reference rides
// libcurl easy/multi; this environment has no libcurl headers, so the
// transport is a POSIX-socket keep-alive connection pool with the same
// wire behavior: scatter-gather request bodies (JSON header + raw tensor
// sections, no copy of tensor data into the body), the
// Inference-Header-Content-Length framing, TCP_NODELAY, and an async path
// on a worker thread pool (role of the reference's curl-multi
// AsyncTransfer loop, http_client.cc:1883-1968).

#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>
#include <thread>

#include "common.h"
#include "tjson.h"
#include "tls.h"

namespace tc {

class HttpConnectionPool;

// TLS settings for https:// server URLs (API parity with the reference's
// curl-backed struct, reference http_client.h:46-87; served here by the
// dlopen'd-OpenSSL transport in tls.h).  verify_peer/verify_host keep the
// curl numeric convention: 0 disables, the defaults (1/2) enable.
struct HttpSslOptions {
  enum CERTTYPE { CERT_PEM = 0, CERT_DER = 1 };
  enum KEYTYPE { KEY_PEM = 0, KEY_DER = 1 };
  long verify_peer = 1;
  long verify_host = 2;
  std::string ca_info;       // PEM roots; empty = system default paths
  CERTTYPE cert_type = CERT_PEM;  // only PEM is supported
  std::string cert;          // client certificate chain
  KEYTYPE key_type = KEY_PEM;     // only PEM is supported
  std::string key;           // client private key
};

//==============================================================================
class InferenceServerHttpClient : public InferenceServerClient {
 public:
  // server_url may carry an http:// or https:// scheme; https enables
  // TLS with ssl_options (reference http_client.h:152-157).
  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& server_url, bool verbose = false,
      int concurrency = 4,
      const HttpSslOptions& ssl_options = HttpSslOptions());

  ~InferenceServerHttpClient();

  Error IsServerLive(bool* live);
  Error IsServerReady(bool* ready);
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "");

  Error ServerMetadata(std::string* server_metadata);
  Error ModelMetadata(
      std::string* model_metadata, const std::string& model_name,
      const std::string& model_version = "");
  Error ModelConfig(
      std::string* model_config, const std::string& model_name,
      const std::string& model_version = "");

  Error ModelRepositoryIndex(std::string* repository_index);
  Error LoadModel(
      const std::string& model_name, const std::string& config = "");
  Error UnloadModel(const std::string& model_name);

  Error ModelInferenceStatistics(
      std::string* infer_stat, const std::string& model_name = "",
      const std::string& model_version = "");

  Error UpdateTraceSettings(
      std::string* response, const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {});
  Error GetTraceSettings(
      std::string* settings, const std::string& model_name = "");

  Error UpdateLogSettings(
      std::string* response, const std::string& settings_json);
  Error GetLogSettings(std::string* settings);

  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0);
  Error UnregisterSystemSharedMemory(const std::string& name = "");
  Error SystemSharedMemoryStatus(std::string* status);

  // XLA/TPU shared memory — generalization of the reference's CUDA verbs
  // (reference http_client.h:411-442): raw_handle is the base64 handle
  // from the xla shm utility library.
  Error RegisterXlaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_ordinal = 0);
  Error UnregisterXlaSharedMemory(const std::string& name = "");
  Error XlaSharedMemoryStatus(std::string* status);

  Error RegisterCudaSharedMemory(
      const std::string& name, const std::string& raw_handle,
      size_t byte_size, int device_id = 0);
  Error UnregisterCudaSharedMemory(const std::string& name = "");
  Error CudaSharedMemoryStatus(std::string* status);

  // Compression algorithms: "" (none), "gzip", "deflate" — request-side
  // body compression and response-side Accept-Encoding (reference
  // http_client.cc:563-580 CompressInput via libcurl; zlib here).
  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs =
          std::vector<const InferRequestedOutput*>(),
      const std::string& request_compression_algorithm = "",
      const std::string& response_compression_algorithm = "");

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs =
          std::vector<const InferRequestedOutput*>(),
      const std::string& request_compression_algorithm = "",
      const std::string& response_compression_algorithm = "");

  // Build an inference request body without sending (reference
  // http_client.h:122-138). Returns body and the JSON header length.
  static Error GenerateRequestBody(
      std::vector<uint8_t>* request_body, size_t* header_length,
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs);

  // Parse a raw response body into an InferResult.
  static Error ParseResponseBody(
      InferResult** result, const std::vector<uint8_t>& response_body,
      size_t header_length);

 private:
  InferenceServerHttpClient(
      const std::string& url, bool verbose, int concurrency,
      const HttpSslOptions& ssl_options);

  Error Get(
      const std::string& path, long* http_code, std::string* response);
  Error Post(
      const std::string& path, const std::string& body, long* http_code,
      std::string* response,
      const std::map<std::string, std::string>& headers = {});
  Error PostBinary(
      const std::string& path, const std::vector<uint8_t>& body,
      size_t header_length, long* http_code, std::string* response,
      size_t* response_header_length, uint64_t timeout_us,
      const std::string& extra_headers = "",
      std::string* response_content_encoding = nullptr);

  std::string host_;
  int port_;
  std::unique_ptr<HttpConnectionPool> pool_;

  // async worker pool
  void AsyncWorker();
  std::vector<std::thread> workers_;
  std::deque<std::function<void()>> async_queue_;
  std::mutex async_mu_;
  std::condition_variable async_cv_;
};

}  // namespace tc
