#include "grpc_client.h"

#include <chrono>
#include <cstdlib>
#include <cstring>
#include <iostream>
#include <map>

namespace tc {

namespace {

constexpr const char kService[] = "inference.GRPCInferenceService";

// ---------------------------------------------------------------------------
// Channel cache: channels to one url are shared across clients up to a
// share count (reference grpc_client.cc:78-145).
//
struct CachedChannel {
  std::shared_ptr<h2::GrpcChannel> channel;
  int use_count = 0;
};

std::mutex channel_cache_mu_;
std::map<std::string, std::vector<CachedChannel>> channel_cache_;

int
MaxShareCount()
{
  const char* env = std::getenv("TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT");
  if (env != nullptr) {
    try {
      int v = std::stoi(env);
      return (v < 1) ? 1 : v;
    }
    catch (...) {
    }
  }
  return 6;
}

Error
AcquireChannel(
    std::shared_ptr<h2::GrpcChannel>* channel, const std::string& url,
    bool verbose, const TlsOptions& tls = TlsOptions())
{
  std::lock_guard<std::mutex> lk(channel_cache_mu_);
  // TLS channels never share a cache slot with cleartext ones (nor with
  // TLS channels built from different credentials)
  std::string cache_key = url;
  if (tls.enabled) {
    cache_key = "tls|" + tls.ca_file + "|" + tls.cert_file + "|" +
                tls.key_file + "|" + url;
  }
  auto& entries = channel_cache_[cache_key];
  const int max_share = MaxShareCount();
  for (auto& e : entries) {
    if (e.use_count < max_share && e.channel->Alive()) {
      e.use_count++;
      *channel = e.channel;
      return Error::Success;
    }
  }
  std::shared_ptr<h2::GrpcChannel> fresh;
  Error err = h2::GrpcChannel::Create(&fresh, url, verbose, tls);
  if (!err.IsOk()) {
    return err;
  }
  entries.push_back(CachedChannel{fresh, 1});
  *channel = std::move(fresh);
  return Error::Success;
}

void
ReleaseChannel(const std::shared_ptr<h2::GrpcChannel>& channel)
{
  std::lock_guard<std::mutex> lk(channel_cache_mu_);
  // scan every bucket: TLS channels cache under a credential-qualified
  // key, not the bare URL (see AcquireChannel)
  for (auto it = channel_cache_.begin(); it != channel_cache_.end(); ++it) {
    auto& entries = it->second;
    for (auto eit = entries.begin(); eit != entries.end(); ++eit) {
      if (eit->channel == channel) {
        if (--eit->use_count <= 0) {
          entries.erase(eit);
        }
        if (entries.empty()) {
          channel_cache_.erase(it);
        }
        return;
      }
    }
  }
}

}  // namespace

//==============================================================================
// InferResultGrpc

Error
InferResultGrpc::Create(
    InferResult** infer_result,
    std::shared_ptr<inference::ModelInferResponse> response)
{
  *infer_result = new InferResultGrpc(std::move(response));
  return Error::Success;
}

Error
InferResultGrpc::Create(
    InferResult** infer_result,
    std::shared_ptr<inference::ModelStreamInferResponse> stream_response)
{
  auto* result = new InferResultGrpc(std::shared_ptr<
                                     inference::ModelInferResponse>(
      stream_response, stream_response->mutable_infer_response()));
  result->stream_response_ = std::move(stream_response);
  if (!result->stream_response_->error_message().empty()) {
    result->status_ = Error(result->stream_response_->error_message());
  }
  *infer_result = result;
  return Error::Success;
}

InferResultGrpc::InferResultGrpc(
    std::shared_ptr<inference::ModelInferResponse> response)
    : response_(std::move(response))
{
}

Error
InferResultGrpc::Output(
    const std::string& name,
    const inference::ModelInferResponse::InferOutputTensor** tensor,
    size_t* index) const
{
  for (int i = 0; i < response_->outputs_size(); ++i) {
    if (response_->outputs(i).name() == name) {
      *tensor = &response_->outputs(i);
      *index = i;
      return Error::Success;
    }
  }
  return Error("output '" + name + "' not found in result");
}

Error
InferResultGrpc::ModelName(std::string* name) const
{
  *name = response_->model_name();
  return Error::Success;
}

Error
InferResultGrpc::ModelVersion(std::string* version) const
{
  *version = response_->model_version();
  return Error::Success;
}

Error
InferResultGrpc::Id(std::string* id) const
{
  *id = response_->id();
  return Error::Success;
}

Error
InferResultGrpc::Shape(
    const std::string& output_name, std::vector<int64_t>* shape) const
{
  const inference::ModelInferResponse::InferOutputTensor* tensor;
  size_t index;
  Error err = Output(output_name, &tensor, &index);
  if (!err.IsOk()) {
    return err;
  }
  shape->assign(tensor->shape().begin(), tensor->shape().end());
  return Error::Success;
}

Error
InferResultGrpc::Datatype(
    const std::string& output_name, std::string* datatype) const
{
  const inference::ModelInferResponse::InferOutputTensor* tensor;
  size_t index;
  Error err = Output(output_name, &tensor, &index);
  if (!err.IsOk()) {
    return err;
  }
  *datatype = tensor->datatype();
  return Error::Success;
}

Error
InferResultGrpc::RawData(
    const std::string& output_name, const uint8_t** buf,
    size_t* byte_size) const
{
  const inference::ModelInferResponse::InferOutputTensor* tensor;
  size_t index;
  Error err = Output(output_name, &tensor, &index);
  if (!err.IsOk()) {
    return err;
  }
  if (static_cast<int>(index) >= response_->raw_output_contents_size()) {
    return Error(
        "output '" + output_name +
        "' has no raw data (shared-memory output or typed contents)");
  }
  const std::string& raw = response_->raw_output_contents(index);
  *buf = reinterpret_cast<const uint8_t*>(raw.data());
  *byte_size = raw.size();
  return Error::Success;
}

Error
InferResultGrpc::StringData(
    const std::string& output_name,
    std::vector<std::string>* string_result) const
{
  const uint8_t* buf;
  size_t byte_size;
  Error err = RawData(output_name, &buf, &byte_size);
  if (!err.IsOk()) {
    return err;
  }
  string_result->clear();
  size_t pos = 0;
  while (pos + 4 <= byte_size) {
    uint32_t len;
    std::memcpy(&len, buf + pos, 4);
    pos += 4;
    if (pos + len > byte_size) {
      return Error("malformed BYTES tensor in output '" + output_name + "'");
    }
    string_result->emplace_back(
        reinterpret_cast<const char*>(buf + pos), len);
    pos += len;
  }
  return Error::Success;
}

std::string
InferResultGrpc::DebugString() const
{
  return response_->ShortDebugString();
}

Error
InferResultGrpc::RequestStatus() const
{
  return status_;
}

//==============================================================================
// InferenceServerGrpcClient

Error
InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& server_url, bool verbose, bool use_ssl,
    const SslOptions& ssl_options, const KeepAliveOptions& keepalive_options)
{
  TlsOptions tls;
  if (use_ssl) {
    std::string why;
    if (!TlsSession::Available(&why)) {
      return Error("use_ssl requested but " + why);
    }
    // reference SslOptions fields are PEM file paths
    // (reference grpc_client.h:43-63); empty roots = system defaults
    tls.enabled = true;
    tls.ca_file = ssl_options.root_certificates;
    tls.cert_file = ssl_options.certificate_chain;
    tls.key_file = ssl_options.private_key;
    tls.alpn = {"h2"};
  }
  std::shared_ptr<h2::GrpcChannel> channel;
  Error err = AcquireChannel(&channel, server_url, verbose, tls);
  if (!err.IsOk()) {
    return err;
  }
  client->reset(new InferenceServerGrpcClient(
      std::move(channel), verbose, keepalive_options));
  return Error::Success;
}

InferenceServerGrpcClient::InferenceServerGrpcClient(
    std::shared_ptr<h2::GrpcChannel> channel, bool verbose,
    const KeepAliveOptions& keepalive_options)
    : InferenceServerClient(verbose), channel_(std::move(channel)),
      keepalive_options_(keepalive_options)
{
  worker_ = std::thread(&InferenceServerGrpcClient::DispatchWorker, this);
  if (keepalive_options_.keepalive_time_ms > 0 &&
      keepalive_options_.keepalive_time_ms < INT32_MAX) {
    keepalive_thread_ =
        std::thread(&InferenceServerGrpcClient::KeepAliveWorker, this);
  }
}

InferenceServerGrpcClient::~InferenceServerGrpcClient()
{
  StopStream();
  {
    std::lock_guard<std::mutex> lk(keepalive_mu_);
    keepalive_exit_ = true;
  }
  keepalive_cv_.notify_all();
  if (keepalive_thread_.joinable()) {
    keepalive_thread_.join();
  }
  // Cancel and drain in-flight AsyncInfer calls: their completions run on
  // the h2 reader thread and enqueue onto this client's worker — neither
  // may happen after teardown.  Cancel outside async_mu_ (CancelStream
  // fires on_close synchronously, which re-enters async_mu_).
  std::vector<h2::GrpcCall> pending;
  {
    std::lock_guard<std::mutex> lk(async_mu_);
    for (auto& kv : outstanding_calls_) {
      pending.push_back(kv.second);
    }
  }
  for (auto& call : pending) {
    call.Cancel();
  }
  {
    std::unique_lock<std::mutex> lk(async_mu_);
    async_cv_.wait_for(lk, std::chrono::seconds(10), [&]() {
      return outstanding_async_ == 0;
    });
  }
  {
    std::lock_guard<std::mutex> lk(worker_mu_);
    worker_exit_ = true;
  }
  worker_cv_.notify_all();
  if (worker_.joinable()) {
    worker_.join();
  }
  ReleaseChannel(channel_);
}

void
InferenceServerGrpcClient::KeepAliveWorker()
{
  uint64_t last_activity = call_activity_.load();
  int pings_without_data = 0;
  for (;;) {
    {
      std::unique_lock<std::mutex> lk(keepalive_mu_);
      keepalive_cv_.wait_for(
          lk,
          std::chrono::milliseconds(keepalive_options_.keepalive_time_ms),
          [&]() { return keepalive_exit_; });
      if (keepalive_exit_) {
        return;
      }
    }
    if (!channel_->Alive()) {
      return;
    }
    const uint64_t activity = call_activity_.load();
    if (activity != last_activity) {
      last_activity = activity;
      pings_without_data = 0;
    } else if (!keepalive_options_.keepalive_permit_without_calls) {
      continue;  // idle and not permitted to ping without calls
    }
    // gRPC semantics: 0 means unlimited pings without data; a positive
    // cap avoids the server's GOAWAY(too_many_pings) protection
    const int max_pings = keepalive_options_.http2_max_pings_without_data;
    if (max_pings > 0 && pings_without_data >= max_pings) {
      continue;
    }
    Error err = channel_->Ping(keepalive_options_.keepalive_timeout_ms);
    if (err.IsOk()) {
      keepalive_pings_.fetch_add(1);
      ++pings_without_data;
    } else if (channel_->Alive()) {
      // missed ack on a connection that still looks up: the peer is
      // unreachable (half-dead link) — declare death so in-flight RPCs
      // fail fast instead of hanging forever
      channel_->Shutdown();
      return;
    } else {
      return;  // connection already torn down
    }
  }
}

void
InferenceServerGrpcClient::DispatchWorker()
{
  for (;;) {
    std::function<void()> fn;
    {
      std::unique_lock<std::mutex> lk(worker_mu_);
      worker_cv_.wait(
          lk, [&]() { return worker_exit_ || !worker_queue_.empty(); });
      if (worker_queue_.empty()) {
        if (worker_exit_) {
          return;
        }
        continue;
      }
      fn = std::move(worker_queue_.front());
      worker_queue_.pop_front();
    }
    fn();
  }
}

void
InferenceServerGrpcClient::EnqueueCallback(std::function<void()> fn)
{
  {
    std::lock_guard<std::mutex> lk(worker_mu_);
    worker_queue_.push_back(std::move(fn));
  }
  worker_cv_.notify_all();
}

template <typename Req, typename Resp>
Error
InferenceServerGrpcClient::Rpc(
    const std::string& method, const Req& request, Resp* response,
    uint64_t timeout_us)
{
  std::string serialized;
  if (!request.SerializeToString(&serialized)) {
    return Error("failed to serialize " + method + " request");
  }
  call_activity_.fetch_add(1);
  std::string out;
  Error err = channel_->Unary(kService, method, serialized, &out, timeout_us);
  if (!err.IsOk()) {
    return err;
  }
  if (!response->ParseFromString(out)) {
    return Error("failed to parse " + method + " response");
  }
  if (verbose_) {
    std::cerr << method << ": " << response->ShortDebugString() << std::endl;
  }
  return Error::Success;
}

Error
InferenceServerGrpcClient::IsServerLive(bool* live)
{
  inference::ServerLiveRequest request;
  inference::ServerLiveResponse response;
  Error err = Rpc("ServerLive", request, &response);
  if (err.IsOk()) {
    *live = response.live();
  }
  return err;
}

Error
InferenceServerGrpcClient::IsServerReady(bool* ready)
{
  inference::ServerReadyRequest request;
  inference::ServerReadyResponse response;
  Error err = Rpc("ServerReady", request, &response);
  if (err.IsOk()) {
    *ready = response.ready();
  }
  return err;
}

Error
InferenceServerGrpcClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version)
{
  inference::ModelReadyRequest request;
  request.set_name(model_name);
  request.set_version(model_version);
  inference::ModelReadyResponse response;
  Error err = Rpc("ModelReady", request, &response);
  if (err.IsOk()) {
    *ready = response.ready();
  }
  return err;
}

Error
InferenceServerGrpcClient::ServerMetadata(
    inference::ServerMetadataResponse* server_metadata)
{
  inference::ServerMetadataRequest request;
  return Rpc("ServerMetadata", request, server_metadata);
}

Error
InferenceServerGrpcClient::ModelMetadata(
    inference::ModelMetadataResponse* model_metadata,
    const std::string& model_name, const std::string& model_version)
{
  inference::ModelMetadataRequest request;
  request.set_name(model_name);
  request.set_version(model_version);
  return Rpc("ModelMetadata", request, model_metadata);
}

Error
InferenceServerGrpcClient::ModelConfig(
    inference::ModelConfigResponse* model_config,
    const std::string& model_name, const std::string& model_version)
{
  inference::ModelConfigRequest request;
  request.set_name(model_name);
  request.set_version(model_version);
  return Rpc("ModelConfig", request, model_config);
}

Error
InferenceServerGrpcClient::ModelRepositoryIndex(
    inference::RepositoryIndexResponse* repository_index)
{
  inference::RepositoryIndexRequest request;
  return Rpc("RepositoryIndex", request, repository_index);
}

Error
InferenceServerGrpcClient::LoadModel(
    const std::string& model_name, const std::string& config)
{
  inference::RepositoryModelLoadRequest request;
  request.set_model_name(model_name);
  if (!config.empty()) {
    (*request.mutable_parameters())["config"].set_string_param(config);
  }
  inference::RepositoryModelLoadResponse response;
  return Rpc("RepositoryModelLoad", request, &response);
}

Error
InferenceServerGrpcClient::UnloadModel(const std::string& model_name)
{
  inference::RepositoryModelUnloadRequest request;
  request.set_model_name(model_name);
  inference::RepositoryModelUnloadResponse response;
  return Rpc("RepositoryModelUnload", request, &response);
}

Error
InferenceServerGrpcClient::ModelInferenceStatistics(
    inference::ModelStatisticsResponse* infer_stat,
    const std::string& model_name, const std::string& model_version)
{
  inference::ModelStatisticsRequest request;
  request.set_name(model_name);
  request.set_version(model_version);
  return Rpc("ModelStatistics", request, infer_stat);
}

Error
InferenceServerGrpcClient::UpdateTraceSettings(
    inference::TraceSettingResponse* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings)
{
  inference::TraceSettingRequest request;
  request.set_model_name(model_name);
  for (const auto& kv : settings) {
    auto& value = (*request.mutable_settings())[kv.first];
    for (const auto& v : kv.second) {
      value.add_value(v);
    }
  }
  return Rpc("TraceSetting", request, response);
}

Error
InferenceServerGrpcClient::GetTraceSettings(
    inference::TraceSettingResponse* settings, const std::string& model_name)
{
  inference::TraceSettingRequest request;
  request.set_model_name(model_name);
  return Rpc("TraceSetting", request, settings);
}

Error
InferenceServerGrpcClient::UpdateLogSettings(
    inference::LogSettingsResponse* response,
    const std::map<std::string, std::string>& settings)
{
  inference::LogSettingsRequest request;
  for (const auto& kv : settings) {
    auto& value = (*request.mutable_settings())[kv.first];
    if (kv.second == "true" || kv.second == "false") {
      value.set_bool_param(kv.second == "true");
    } else if (
        !kv.second.empty() &&
        kv.second.find_first_not_of("0123456789") == std::string::npos) {
      value.set_uint32_param(
          static_cast<uint32_t>(std::stoul(kv.second)));
    } else {
      value.set_string_param(kv.second);
    }
  }
  return Rpc("LogSettings", request, response);
}

Error
InferenceServerGrpcClient::GetLogSettings(
    inference::LogSettingsResponse* settings)
{
  inference::LogSettingsRequest request;
  return Rpc("LogSettings", request, settings);
}

Error
InferenceServerGrpcClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset)
{
  inference::SystemSharedMemoryRegisterRequest request;
  request.set_name(name);
  request.set_key(key);
  request.set_offset(offset);
  request.set_byte_size(byte_size);
  inference::SystemSharedMemoryRegisterResponse response;
  return Rpc("SystemSharedMemoryRegister", request, &response);
}

Error
InferenceServerGrpcClient::UnregisterSystemSharedMemory(
    const std::string& name)
{
  inference::SystemSharedMemoryUnregisterRequest request;
  request.set_name(name);
  inference::SystemSharedMemoryUnregisterResponse response;
  return Rpc("SystemSharedMemoryUnregister", request, &response);
}

Error
InferenceServerGrpcClient::SystemSharedMemoryStatus(
    inference::SystemSharedMemoryStatusResponse* status)
{
  inference::SystemSharedMemoryStatusRequest request;
  return Rpc("SystemSharedMemoryStatus", request, status);
}

Error
InferenceServerGrpcClient::RegisterXlaSharedMemory(
    const std::string& name, const std::string& raw_handle, size_t byte_size,
    int device_ordinal)
{
  inference::XlaSharedMemoryRegisterRequest request;
  request.set_name(name);
  request.set_raw_handle(raw_handle);
  request.set_device_ordinal(device_ordinal);
  request.set_byte_size(byte_size);
  inference::XlaSharedMemoryRegisterResponse response;
  return Rpc("XlaSharedMemoryRegister", request, &response);
}

Error
InferenceServerGrpcClient::UnregisterXlaSharedMemory(const std::string& name)
{
  inference::XlaSharedMemoryUnregisterRequest request;
  request.set_name(name);
  inference::XlaSharedMemoryUnregisterResponse response;
  return Rpc("XlaSharedMemoryUnregister", request, &response);
}

Error
InferenceServerGrpcClient::XlaSharedMemoryStatus(
    inference::XlaSharedMemoryStatusResponse* status)
{
  inference::XlaSharedMemoryStatusRequest request;
  return Rpc("XlaSharedMemoryStatus", request, status);
}

Error
InferenceServerGrpcClient::RegisterCudaSharedMemory(
    const std::string& name, const std::string& raw_handle, size_t byte_size,
    int device_id)
{
  inference::CudaSharedMemoryRegisterRequest request;
  request.set_name(name);
  request.set_raw_handle(raw_handle);
  request.set_device_id(device_id);
  request.set_byte_size(byte_size);
  inference::CudaSharedMemoryRegisterResponse response;
  return Rpc("CudaSharedMemoryRegister", request, &response);
}

Error
InferenceServerGrpcClient::UnregisterCudaSharedMemory(const std::string& name)
{
  inference::CudaSharedMemoryUnregisterRequest request;
  request.set_name(name);
  inference::CudaSharedMemoryUnregisterResponse response;
  return Rpc("CudaSharedMemoryUnregister", request, &response);
}

Error
InferenceServerGrpcClient::CudaSharedMemoryStatus(
    inference::CudaSharedMemoryStatusResponse* status)
{
  inference::CudaSharedMemoryStatusRequest request;
  return Rpc("CudaSharedMemoryStatus", request, status);
}

Error
InferenceServerGrpcClient::PreRunProcessing(
    inference::ModelInferRequest* request, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  request->Clear();
  request->set_model_name(options.model_name_);
  request->set_model_version(options.model_version_);
  request->set_id(options.request_id_);

  auto& params = *request->mutable_parameters();
  if (options.sequence_id_ != 0) {
    params["sequence_id"].set_uint64_param(options.sequence_id_);
    params["sequence_start"].set_bool_param(options.sequence_start_);
    params["sequence_end"].set_bool_param(options.sequence_end_);
  }
  if (options.priority_ != 0) {
    params["priority"].set_uint64_param(options.priority_);
  }
  if (options.server_timeout_us_ != 0) {
    params["timeout"].set_int64_param(options.server_timeout_us_);
  }
  if (options.triton_enable_empty_final_response_) {
    params["triton_enable_empty_final_response"].set_bool_param(true);
  }

  // 2 GB protobuf guard (reference grpc_client.cc:1345-1353)
  size_t total = 0;
  for (const auto* input : inputs) {
    total += input->TotalByteSize();
  }
  if (total > 0x7fffffffull) {
    return Error(
        "inputs exceed the 2 GB protobuf limit; use shared memory for "
        "requests this large");
  }

  for (auto* input : inputs) {
    auto* tensor = request->add_inputs();
    tensor->set_name(input->Name());
    tensor->set_datatype(input->Datatype());
    for (int64_t dim : input->Shape()) {
      tensor->add_shape(dim);
    }
    if (input->IsSharedMemory()) {
      auto& tp = *tensor->mutable_parameters();
      tp["shared_memory_region"].set_string_param(input->SharedMemoryName());
      tp["shared_memory_byte_size"].set_int64_param(
          input->SharedMemoryByteSize());
      if (input->SharedMemoryOffset() != 0) {
        tp["shared_memory_offset"].set_int64_param(
            input->SharedMemoryOffset());
      }
    } else {
      std::string* raw = request->add_raw_input_contents();
      raw->reserve(input->TotalByteSize());
      input->PrepareForRequest();
      const uint8_t* buf;
      size_t len;
      bool end;
      while (input->GetNext(&buf, &len, &end).IsOk()) {
        if (buf != nullptr) {
          raw->append(reinterpret_cast<const char*>(buf), len);
        }
        if (end) {
          break;
        }
      }
    }
  }

  for (const auto* output : outputs) {
    auto* tensor = request->add_outputs();
    tensor->set_name(output->Name());
    auto& tp = *tensor->mutable_parameters();
    if (output->ClassCount() > 0) {
      tp["classification"].set_int64_param(output->ClassCount());
    }
    if (output->IsSharedMemory()) {
      tp["shared_memory_region"].set_string_param(output->SharedMemoryName());
      tp["shared_memory_byte_size"].set_int64_param(
          output->SharedMemoryByteSize());
      if (output->SharedMemoryOffset() != 0) {
        tp["shared_memory_offset"].set_int64_param(
            output->SharedMemoryOffset());
      }
    }
  }
  return Error::Success;
}

Error
InferenceServerGrpcClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  RequestTimers timer;
  timer.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  timer.CaptureTimestamp(RequestTimers::Kind::SEND_START);
  Error err = PreRunProcessing(&sync_request_, options, inputs, outputs);
  if (!err.IsOk()) {
    return err;
  }
  std::string serialized;
  if (!sync_request_.SerializeToString(&serialized)) {
    return Error("failed to serialize ModelInfer request");
  }
  timer.CaptureTimestamp(RequestTimers::Kind::SEND_END);

  call_activity_.fetch_add(1);
  std::string out;
  err = channel_->Unary(
      kService, "ModelInfer", serialized, &out, options.client_timeout_us_,
      CompressionHeaders());
  if (!err.IsOk()) {
    return err;
  }

  timer.CaptureTimestamp(RequestTimers::Kind::RECV_START);
  auto response = std::make_shared<inference::ModelInferResponse>();
  if (!response->ParseFromString(out)) {
    return Error("failed to parse ModelInfer response");
  }
  timer.CaptureTimestamp(RequestTimers::Kind::RECV_END);
  timer.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
  UpdateInferStat(timer);
  if (verbose_) {
    std::cerr << "ModelInfer: " << response->ShortDebugString() << std::endl;
  }
  return InferResultGrpc::Create(result, std::move(response));
}

Error
InferenceServerGrpcClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  auto request = std::make_shared<inference::ModelInferRequest>();
  Error err = PreRunProcessing(request.get(), options, inputs, outputs);
  if (!err.IsOk()) {
    return err;
  }
  std::string serialized;
  if (!request->SerializeToString(&serialized)) {
    return Error("failed to serialize ModelInfer request");
  }

  auto timer = std::make_shared<RequestTimers>();
  timer->CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  timer->CaptureTimestamp(RequestTimers::Kind::SEND_START);

  call_activity_.fetch_add(1);
  uint64_t call_id;
  {
    std::lock_guard<std::mutex> lk(async_mu_);
    call_id = next_async_id_++;
    ++outstanding_async_;
  }
  // Exactly-once report claim: either the completion path fires the user
  // callback, or a Write failure is returned to the caller — whoever
  // flips this first owns the report (they can race when the server
  // resets the stream immediately after StartCall).
  auto reported = std::make_shared<std::atomic<bool>>(false);

  auto response_buf = std::make_shared<std::string>();
  h2::GrpcCall call;
  {
    // Track before StartCall: on_done may fire on the reader thread
    // before StartCall even returns, and it must find (and erase) the
    // entry rather than race a later insertion.
    std::lock_guard<std::mutex> lk(async_mu_);
    outstanding_calls_.emplace(call_id, call);
  }
  err = channel_->StartCall(
      &call, kService, "ModelInfer",
      [response_buf](std::string&& msg) { *response_buf = std::move(msg); },
      [this, callback, timer, response_buf, call_id, reported](
          Error e, int status, std::string message) {
        // completion runs on the reader thread; hand the user callback to
        // the dispatch worker (role of the reference's AsyncTransfer
        // thread, grpc_client.cc:1483-1527)
        if (!reported->exchange(true)) {
          EnqueueCallback([this, callback, timer, response_buf, e, status,
                           message]() {
            InferResult* result = nullptr;
            auto response = std::make_shared<inference::ModelInferResponse>();
            Error final_err = e;
            if (final_err.IsOk() && status != 0) {
              final_err = Error(
                  message.empty() ? ("grpc-status " + std::to_string(status))
                                  : message);
            }
            if (final_err.IsOk() &&
                !response->ParseFromString(*response_buf)) {
              final_err = Error("failed to parse ModelInfer response");
            }
            timer->CaptureTimestamp(RequestTimers::Kind::RECV_START);
            timer->CaptureTimestamp(RequestTimers::Kind::RECV_END);
            timer->CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
            if (final_err.IsOk()) {
              UpdateInferStat(*timer);
            }
            InferResultGrpc::Create(&result, std::move(response));
            static_cast<InferResultGrpc*>(result)->SetRequestStatus(final_err);
            callback(result);
          });
        }
        // last touch of `this` on the completion path: the destructor
        // blocks on outstanding_async_ before tearing anything down
        std::lock_guard<std::mutex> lk(async_mu_);
        outstanding_calls_.erase(call_id);
        --outstanding_async_;
        async_cv_.notify_all();
      },
      options.client_timeout_us_, CompressionHeaders());
  if (!err.IsOk()) {
    std::lock_guard<std::mutex> lk(async_mu_);
    outstanding_calls_.erase(call_id);
    --outstanding_async_;
    async_cv_.notify_all();
    return err;
  }
  {
    // fill in the now-started call; skip if on_done already erased it
    std::lock_guard<std::mutex> lk(async_mu_);
    auto it = outstanding_calls_.find(call_id);
    if (it != outstanding_calls_.end()) {
      it->second = call;
    }
  }
  err = call.Write(serialized, /*end_of_calls=*/true);
  timer->CaptureTimestamp(RequestTimers::Kind::SEND_END);
  if (!err.IsOk()) {
    if (reported->exchange(true)) {
      // on_done won the race (e.g. immediate server reset): the outcome
      // is already being delivered via the callback — don't ALSO report
      // an error here or the request would be double-handled
      return Error::Success;
    }
    // we own the report: deliver via this return value; cancel wakes
    // on_close which cleans up the tracking entry without re-firing
    call.Cancel();
    return err;
  }
  return Error::Success;
}

Error
InferenceServerGrpcClient::InferMulti(
    std::vector<InferResult*>* results, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs)
{
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("'options' must be of size 1 or match 'inputs'");
  }
  if (!outputs.empty() && outputs.size() != inputs.size()) {
    return Error("'outputs' must be empty or match 'inputs'");
  }
  results->clear();
  for (size_t i = 0; i < inputs.size(); ++i) {
    const auto& opt = (options.size() == 1) ? options[0] : options[i];
    static const std::vector<const InferRequestedOutput*> kNoOutputs;
    const auto& outs = outputs.empty() ? kNoOutputs : outputs[i];
    InferResult* result = nullptr;
    Error err = Infer(&result, opt, inputs[i], outs);
    if (!err.IsOk()) {
      for (auto* r : *results) {
        delete r;
      }
      results->clear();
      return err;
    }
    results->push_back(result);
  }
  return Error::Success;
}

Error
InferenceServerGrpcClient::AsyncInferMulti(
    OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs)
{
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInferMulti");
  }
  if (options.size() != 1 && options.size() != inputs.size()) {
    return Error("'options' must be of size 1 or match 'inputs'");
  }
  if (!outputs.empty() && outputs.size() != inputs.size()) {
    return Error("'outputs' must be empty or match 'inputs'");
  }
  const size_t n = inputs.size();
  struct MultiState {
    std::mutex mu;
    std::vector<InferResult*> results;
    size_t pending;
    bool failed = false;  // caller was given an error return instead
    OnMultiCompleteFn callback;
  };
  auto state = std::make_shared<MultiState>();
  state->results.resize(n, nullptr);
  state->pending = n;
  state->callback = std::move(callback);
  for (size_t i = 0; i < n; ++i) {
    const auto& opt = (options.size() == 1) ? options[0] : options[i];
    static const std::vector<const InferRequestedOutput*> kNoOutputs;
    const auto& outs = outputs.empty() ? kNoOutputs : outputs[i];
    Error err = AsyncInfer(
        [state, i](InferResult* result) {
          bool fire = false;
          bool cleanup = false;
          {
            std::lock_guard<std::mutex> lk(state->mu);
            state->results[i] = result;
            if (--state->pending == 0) {
              (state->failed ? cleanup : fire) = true;
            }
          }
          if (fire) {
            state->callback(state->results);
          } else if (cleanup) {
            for (auto* r : state->results) {
              delete r;
            }
          }
        },
        opt, inputs[i], outs);
    if (!err.IsOk()) {
      // slots [i, n) will never produce callbacks; account for them so
      // the already-dispatched results are still freed, and suppress the
      // multi-callback — the caller is getting this error return instead
      bool cleanup = false;
      {
        std::lock_guard<std::mutex> lk(state->mu);
        state->failed = true;
        state->pending -= (n - i);
        cleanup = (state->pending == 0);
      }
      if (cleanup) {
        for (auto* r : state->results) {
          delete r;
        }
      }
      return err;
    }
  }
  return Error::Success;
}

Error
InferenceServerGrpcClient::StartStream(
    OnCompleteFn stream_callback, bool enable_stats,
    uint64_t stream_timeout_us)
{
  if (stream_callback == nullptr) {
    return Error("callback must not be null for StartStream");
  }
  std::lock_guard<std::mutex> lk(stream_mu_);
  if (stream_call_ != nullptr) {
    return Error("stream is already active");
  }
  stream_callback_ = std::move(stream_callback);
  stream_enable_stats_ = enable_stats;
  stream_done_ = false;
  stream_status_ = Error::Success;
  stream_timers_.clear();

  auto call = std::make_unique<h2::GrpcCall>();
  Error err = channel_->StartCall(
      call.get(), kService, "ModelStreamInfer",
      [this](std::string&& msg) {
        auto response = std::make_shared<inference::ModelStreamInferResponse>();
        if (!response->ParseFromString(msg)) {
          return;  // a malformed frame is surfaced via stream close
        }
        EnqueueCallback([this, response]() {
          RequestTimers timer;
          bool have_timer = false;
          {
            std::lock_guard<std::mutex> slk(stream_mu_);
            if (!stream_timers_.empty()) {
              timer = stream_timers_.front();
              stream_timers_.pop_front();
              have_timer = true;
            }
          }
          if (have_timer && stream_enable_stats_ &&
              response->error_message().empty()) {
            timer.CaptureTimestamp(RequestTimers::Kind::RECV_START);
            timer.CaptureTimestamp(RequestTimers::Kind::RECV_END);
            timer.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
            UpdateInferStat(timer);
          }
          // StopStream clears stream_callback_ as soon as stream_done_ is
          // observed; messages already queued here must not invoke a null
          // std::function — snapshot under stream_mu_ and skip when gone.
          OnCompleteFn cb;
          {
            std::lock_guard<std::mutex> slk(stream_mu_);
            cb = stream_callback_;
          }
          if (cb == nullptr) {
            return;
          }
          InferResult* result = nullptr;
          InferResultGrpc::Create(&result, response);
          cb(result);
        });
      },
      [this](Error e, int status, std::string message) {
        std::lock_guard<std::mutex> slk(stream_mu_);
        stream_done_ = true;
        if (!e.IsOk()) {
          stream_status_ = e;
        } else if (status != 0) {
          stream_status_ = Error(
              message.empty() ? ("grpc-status " + std::to_string(status))
                              : message);
        }
        stream_cv_.notify_all();
      },
      stream_timeout_us, CompressionHeaders());
  if (!err.IsOk()) {
    return err;
  }
  stream_call_ = std::move(call);
  return Error::Success;
}

Error
InferenceServerGrpcClient::StopStream()
{
  std::unique_lock<std::mutex> lk(stream_mu_);
  if (stream_call_ == nullptr) {
    return Error::Success;
  }
  stream_call_->WritesDone();
  if (!stream_cv_.wait_for(
          lk, std::chrono::seconds(10), [&]() { return stream_done_; })) {
    stream_call_->Cancel();
    stream_cv_.wait_for(
        lk, std::chrono::seconds(2), [&]() { return stream_done_; });
  }
  Error status = stream_status_;
  stream_call_.reset();
  stream_callback_ = nullptr;
  lk.unlock();
  // Quiesce: response callbacks already queued on the dispatch worker
  // may still be executing (they snapshot stream_callback_ before this
  // cleared it).  Wait for a sentinel to flow through the queue so no
  // user callback runs after StopStream returns — callers may destroy
  // state their callback captures by reference right after this.
  // Skipped when StopStream runs ON the worker (a stream callback
  // stopping its own stream): the sentinel could never be dequeued.
  if (std::this_thread::get_id() != worker_.get_id()) {
    struct DrainState {
      std::mutex mu;
      std::condition_variable cv;
      bool drained = false;
    };
    auto state = std::make_shared<DrainState>();
    EnqueueCallback([state]() {
      std::lock_guard<std::mutex> dlk(state->mu);
      state->drained = true;
      state->cv.notify_all();
    });
    std::unique_lock<std::mutex> dlk(state->mu);
    state->cv.wait_for(
        dlk, std::chrono::seconds(10), [&]() { return state->drained; });
  }
  return status;
}

Error
InferenceServerGrpcClient::AsyncStreamInfer(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs)
{
  inference::ModelInferRequest request;
  Error err = PreRunProcessing(&request, options, inputs, outputs);
  if (!err.IsOk()) {
    return err;
  }
  std::string serialized;
  if (!request.SerializeToString(&serialized)) {
    return Error("failed to serialize stream request");
  }
  RequestTimers timer;
  timer.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  timer.CaptureTimestamp(RequestTimers::Kind::SEND_START);
  std::lock_guard<std::mutex> lk(stream_mu_);
  if (stream_call_ == nullptr) {
    return Error("stream is not active; call StartStream first");
  }
  if (stream_done_) {
    return Error(
        stream_status_.IsOk() ? "stream has ended" : stream_status_.Message());
  }
  call_activity_.fetch_add(1);
  err = stream_call_->Write(serialized, /*end_of_calls=*/false);
  if (!err.IsOk()) {
    return err;
  }
  timer.CaptureTimestamp(RequestTimers::Kind::SEND_END);
  if (stream_enable_stats_) {
    stream_timers_.push_back(timer);
  }
  return Error::Success;
}

}  // namespace tc
